"""COCO-faithful detection evaluation in vectorized numpy.

A from-scratch reimplementation of the COCO mAP protocol (the semantics of
pycocotools' ``COCOeval``, which the reference shells out to on CPU from
``detection/mean_ap.py:501``; the reference's pure-torch blueprint is
``detection/_mean_ap.py``):

- IoU thresholds 0.50:0.05:0.95, recall thresholds 0:0.01:1 (101 points),
  max-detection caps (1, 10, 100), area ranges all/small/medium/large;
- per (class, image): detections sorted by score, greedily matched to the
  not-yet-matched ground truth with the highest IoU above the threshold;
  crowd ground truths may match many detections and use a detection-area
  union (``iscrowd`` semantics); ignored ground truths (crowd or
  out-of-area-range) absorb matches without counting;
- accumulation: detections merged across images per class, re-sorted by
  score, TP/FP cumsums over non-ignored entries, precision made monotone
  from the right, sampled at the recall thresholds.

Everything after the per-image matching is dense numpy (the matching itself
is a data-dependent greedy loop, which is why — like the reference — this
runs on host at ``compute`` time; states stay on device until then).

**Batched matching** (the map_ragged hot path): the greedy loop is
sequential only over the detections *within* one (image, class) cell —
cells are independent.  :func:`coco_evaluate` therefore pads every cell of
a class to a shared (D, G) bucket (pow-2 edges, the same shape discipline
as :mod:`tpumetrics.runtime.bucketing`) and runs ONE loop over the padded
detection axis, vectorized across all images × area ranges × IoU
thresholds at once — the Python-dispatch count per compute drops from
O(images × classes × dets) to O(classes × buckets × max_dets).
Accumulation is likewise batched: per (class, max_det cap) the detections
of all images flatten into one score-sorted matrix shared by every area
range.  The per-cell reference implementation is kept verbatim
(:func:`_match_image_areas`, :func:`_accumulate_class_area`,
:func:`coco_evaluate_unfused`) and the batched path is asserted
bit-identical against it in ``tests/detection/test_coco_batched.py``.

The default bbox hot path goes one layer further:
:mod:`tpumetrics.detection._coco_eval_jax` compiles the same bucketed
matching + accumulation into ONE jitted XLA program (bit-identical by
construction, pinned in ``tests/detection/test_map_parity_corpus.py``).
This module remains the oracle, the ``segm``/``extended_summary``/
over-budget path, and the fallback when the jitted path declines.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_AREA_RANGES = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}


def rle_decode_flat(runs: np.ndarray, num_pixels: int) -> np.ndarray:
    """Decode column-major RLE runs (alternating 0s/1s, leading 0-run) to a
    flat (num_pixels,) uint8 vector."""
    runs = np.asarray(runs, dtype=np.int64)
    vals = np.zeros(runs.shape[0], dtype=np.uint8)
    vals[1::2] = 1
    flat = np.repeat(vals, runs)
    if flat.shape[0] != num_pixels:
        raise ValueError(f"RLE decodes to {flat.shape[0]} pixels, expected {num_pixels}")
    return flat


def _pairwise_geometry(
    det_geom, gt_geom, iou_type: str
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Precompute class-independent pairwise pieces for one image: the
    intersection matrix (D, G) and the per-item geometry areas.

    For ``bbox`` the geometry is an xyxy (N, 4) array; for ``segm`` it is
    ``((h, w), [runs, ...])`` — column-major RLE runs per mask.  Masks are
    decoded once per image and intersected with ONE (D, HW) x (HW, G)
    matmul, so the per-class loop below only slices — the pycocotools
    equivalent recomputes ``maskUtils.iou`` per (image, category).
    """
    if iou_type == "bbox":
        det, gt = det_geom, gt_geom
        det_area = (det[:, 2] - det[:, 0]) * (det[:, 3] - det[:, 1]) if det.size else np.zeros(det.shape[0])
        gt_area = (gt[:, 2] - gt[:, 0]) * (gt[:, 3] - gt[:, 1]) if gt.size else np.zeros(gt.shape[0])
        if det.shape[0] == 0 or gt.shape[0] == 0:
            inter = np.zeros((det.shape[0], gt.shape[0]))
        else:
            lt = np.maximum(det[:, None, :2], gt[None, :, :2])
            rb = np.minimum(det[:, None, 2:], gt[None, :, 2:])
            wh = np.clip(rb - lt, 0, None)
            inter = (wh[..., 0] * wh[..., 1]).astype(np.float64)
        return inter, np.asarray(det_area, np.float64), np.asarray(gt_area, np.float64)

    (h, w), det_runs = det_geom
    _, gt_runs = gt_geom
    num_px = h * w
    det_area = np.array([float(np.asarray(r, np.int64)[1::2].sum()) for r in det_runs])
    gt_area = np.array([float(np.asarray(r, np.int64)[1::2].sum()) for r in gt_runs])
    if len(det_runs) == 0 or len(gt_runs) == 0:
        return np.zeros((len(det_runs), len(gt_runs))), det_area, gt_area
    # decode to uint8 and matmul in float32, chunked over detections: f32 is
    # exact for pixel counts < 2^24 (any mask below 16.7 Mpx) at half the
    # float64 footprint, and chunking bounds the peak to the gt matrix plus
    # one chunk rather than the full (D, HW) dense float block
    dmat = np.stack([rle_decode_flat(r, num_px) for r in det_runs])
    gmat32 = np.stack([rle_decode_flat(r, num_px) for r in gt_runs]).astype(np.float32).T
    inter = np.empty((dmat.shape[0], gmat32.shape[1]), dtype=np.float64)
    chunk = max(1, min(dmat.shape[0], (1 << 25) // max(num_px, 1)))  # ~128 MB f32 per chunk
    for i in range(0, dmat.shape[0], chunk):
        inter[i : i + chunk] = dmat[i : i + chunk].astype(np.float32) @ gmat32
    return inter, det_area, gt_area


def _match_image_areas(
    ious: np.ndarray,
    det_areas: np.ndarray,
    det_scores: np.ndarray,
    gt_crowd: np.ndarray,
    gt_area: np.ndarray,
    iou_thresholds: np.ndarray,
    area_ranges: Sequence[Tuple[float, float]],
    max_det: int,
) -> Optional[List[dict]]:
    """Match one (image, class) pair at every (area range, IoU threshold)
    simultaneously (pycocotools ``evaluateImg`` semantics; reference
    _mean_ap.py:521-649).

    ``ious``/``det_areas``/``det_scores`` are already score-sorted
    (descending, stable) — computed once per (image, class) by the caller.
    Only the detection loop is sequential (each det claims a gt); the per-det
    candidate search is vectorized over all (area, threshold, gt) triples —
    area ranges only change which gts are ignored, so evaluating all four in
    one pass quarters the Python-loop overhead of the hot host path.  The
    greedy rules are replicated exactly: non-ignored gts take precedence over
    ignored ones (the reference's sorted-ignored-last + break), ties replace
    (last-wins argmax), crowd gts can absorb any number of detections.
    """
    n_gt = gt_crowd.shape[0]
    n_det = min(det_scores.shape[0], max_det)
    if n_gt == 0 and n_det == 0:
        return None

    lo = np.asarray([r[0] for r in area_ranges])  # (A,)
    hi = np.asarray([r[1] for r in area_ranges])
    crowd = gt_crowd.astype(bool)
    gt_ignore = crowd[None, :] | (gt_area[None, :] < lo[:, None]) | (gt_area[None, :] > hi[:, None])  # (A, G)
    num_areas = len(area_ranges)
    num_thrs = len(iou_thresholds)
    thr = np.minimum(np.asarray(iou_thresholds)[None, :, None], 1 - 1e-10)  # (1, T, 1)
    det_matches = np.zeros((num_areas, num_thrs, n_det), dtype=np.int64)  # 1 if matched
    det_ignore = np.zeros((num_areas, num_thrs, n_det), dtype=bool)
    avail = np.ones((num_areas, num_thrs, n_gt), dtype=bool)  # gt not yet claimed
    ious = ious[:n_det]
    real = ~gt_ignore

    for d_idx in range(n_det):
        iou_row = ious[d_idx][None, None, :]  # (1, 1, G)
        cand = avail & (iou_row >= thr)  # (A, T, G)
        cand_real = cand & real[:, None, :]
        use_real = cand_real.any(axis=2)
        pick_from = np.where(use_real[..., None], cand_real, cand & gt_ignore[:, None, :])
        has = pick_from.any(axis=2)
        if not has.any():
            continue
        vals = np.where(pick_from, iou_row, -1.0)
        best_g = n_gt - 1 - np.argmax(vals[..., ::-1], axis=2)  # last-wins argmax
        rows_a, rows_t = np.nonzero(has)
        bg = best_g[rows_a, rows_t]
        det_matches[rows_a, rows_t, d_idx] = 1
        det_ignore[rows_a, rows_t, d_idx] = gt_ignore[rows_a, bg]
        noncrowd = ~crowd[bg]
        avail[rows_a[noncrowd], rows_t[noncrowd], bg[noncrowd]] = False

    # unmatched detections outside the area range are ignored
    da = det_areas[:n_det]
    det_out_of_range = (da[None, :] < lo[:, None]) | (da[None, :] > hi[:, None])  # (A, D)
    det_ignore = det_ignore | ((det_matches == 0) & det_out_of_range[:, None, :])

    scores = det_scores[:n_det]
    return [
        {
            "det_scores": scores,
            "det_matches": det_matches[a],
            "det_ignore": det_ignore[a],
            "num_gt": int((~gt_ignore[a]).sum()),
        }
        for a in range(num_areas)
    ]




def _accumulate_class_area(
    results: List[Optional[dict]], num_thrs: int, rec_thresholds: np.ndarray, max_det: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Merge per-image matchings of one (class, area, maxdet) cell into
    precision-at-recall-thresholds and best recall (pycocotools
    ``accumulate``; reference _mean_ap.py:696-782).

    ``max_det`` slices each image's (already score-sorted) detections, so the
    greedy matching runs once per (class, area) at the largest cap and is
    reused for the smaller ones — pycocotools does the same."""
    results = [r for r in results if r is not None]
    num_rec = len(rec_thresholds)
    precision = -np.ones((num_thrs, num_rec))
    recall = -np.ones(num_thrs)
    if not results:
        return precision, recall

    m = max_det if max_det is not None else max(r["det_scores"].shape[0] for r in results)
    scores = np.concatenate([r["det_scores"][:m] for r in results])
    matches = np.concatenate([r["det_matches"][:, :m] for r in results], axis=1)
    ignore = np.concatenate([r["det_ignore"][:, :m] for r in results], axis=1)
    npig = sum(r["num_gt"] for r in results)
    if npig == 0:
        return precision, recall

    order = np.argsort(-scores, kind="mergesort")
    matches = matches[:, order]
    ignore = ignore[:, order]

    tps = np.logical_and(matches, ~ignore)
    fps = np.logical_and(~matches.astype(bool), ~ignore)
    tp_sum = np.cumsum(tps, axis=1).astype(np.float64)
    fp_sum = np.cumsum(fps, axis=1).astype(np.float64)

    for t_idx in range(num_thrs):
        tp = tp_sum[t_idx]
        fp = fp_sum[t_idx]
        nd = len(tp)
        rc = tp / npig
        pr = tp / np.maximum(fp + tp, np.finfo(np.float64).eps)
        recall[t_idx] = rc[-1] if nd else 0.0

        # monotone precision envelope from the right (pycocotools loop)
        pr = np.maximum.accumulate(pr[::-1])[::-1]
        inds = np.searchsorted(rc, rec_thresholds, side="left")
        q = np.zeros(num_rec)
        valid = inds < nd
        q[valid] = pr[inds[valid]]
        precision[t_idx] = q
    return precision, recall


# ---------------------------------------------------------- batched matching


# batched-match work budget: N_cells * areas * thresholds * G_pad * D_pad
# elements touched by one bucket's greedy pass.  Under it, ONE bucket per
# class maximizes batching (every Python-level matcher dispatch covers all
# cells); above it, pow-2 sub-buckets bound the padding blow-up a single
# huge image would force on every small cell.
_MATCH_BUDGET = 1 << 24


def _cell_buckets(
    cells: List[Tuple], max_det: int, num_areas: int, num_thrs: int
) -> Dict[Tuple[int, int], List[int]]:
    """Group cell indices by their padded (detection, groundtruth) bucket.

    Fewest-buckets-first: if padding every cell straight to the class max
    stays under ``_MATCH_BUDGET`` (the common case — evaluation corpora are
    ragged but not wild), everything lands in one bucket and the greedy pass
    is a single vectorized loop.  Otherwise cells split along pow-2 edges
    (the :func:`tpumetrics.runtime.bucketing.pow2_bucket_edges` discipline,
    floored at 8 so near-sized cells still share a shape)."""
    from tpumetrics.runtime.bucketing import ShapeBucketer, pow2_bucket_edges

    d_sizes = [max(min(c[2].shape[0], max_det), 1) for c in cells]
    g_sizes = [max(c[3].shape[0], 1) for c in cells]
    d_max, g_max = max(d_sizes, default=1), max(g_sizes, default=1)
    if len(cells) * num_areas * num_thrs * d_max * g_max <= _MATCH_BUDGET:
        return {(d_max, g_max): list(range(len(cells)))}
    floor = 8
    d_bucketer = ShapeBucketer(
        [e for e in pow2_bucket_edges(d_max) if e >= min(floor, d_max)]
    )
    g_bucketer = ShapeBucketer(
        [e for e in pow2_bucket_edges(g_max) if e >= min(floor, g_max)]
    )
    groups: Dict[Tuple[int, int], List[int]] = {}
    for i, (d, g) in enumerate(zip(d_sizes, g_sizes)):
        groups.setdefault((d_bucketer.bucket_for(d), g_bucketer.bucket_for(g)), []).append(i)
    return groups


def _match_cells_batched(
    cells: List[Tuple],
    iou_thresholds: np.ndarray,
    area_ranges: Sequence[Tuple[float, float]],
    max_det: int,
    d_pad: int,
    g_pad: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Greedy-match a batch of same-bucket (image, class) cells at once.

    Semantically identical to running :func:`_match_image_areas` per cell —
    the greedy detection loop is sequential only *within* a cell, so the
    loop below runs over the padded detection axis once, vectorized over
    (cell, area range, IoU threshold, gt) for every cell simultaneously.

    ``cells`` entries are ``(ious, det_areas, det_scores, gt_crowd,
    gt_area)`` with detections already score-sorted and capped to
    ``max_det``.  Padding convention: pad IoUs are ``-1`` (below every
    threshold), pad gts are unavailable and ignored, pad detections are
    marked invalid and excluded by the caller's validity mask.

    Returns ``(det_matches (N, A, T, Dp) bool, det_ignore (N, A, T, Dp)
    bool, scores (N, Dp) f32, det_valid (N, Dp) bool, num_gt (N, A))``.
    """
    n_cells = len(cells)
    num_areas = len(area_ranges)
    num_thrs = len(iou_thresholds)

    ious_p = np.full((n_cells, d_pad, g_pad), -1.0)
    da_p = np.zeros((n_cells, d_pad))
    sc_p = np.zeros((n_cells, d_pad), np.float32)
    crowd_p = np.zeros((n_cells, g_pad), bool)
    ga_p = np.zeros((n_cells, g_pad))
    det_valid = np.zeros((n_cells, d_pad), bool)
    gt_valid = np.zeros((n_cells, g_pad), bool)
    for i, (ious, da, ds, gc, ga) in enumerate(cells):
        d = min(ds.shape[0], max_det)
        g = gc.shape[0]
        ious_p[i, :d, :g] = ious[:d]
        da_p[i, :d] = da[:d]
        sc_p[i, :d] = ds[:d]
        crowd_p[i, :g] = gc.astype(bool)
        ga_p[i, :g] = ga
        det_valid[i, :d] = True
        gt_valid[i, :g] = True

    lo = np.asarray([r[0] for r in area_ranges])
    hi = np.asarray([r[1] for r in area_ranges])
    # (N, A, G): crowd / out-of-range gts absorb matches without counting;
    # pad gts are forced ignored AND unavailable so they can never match
    gt_ignore = (
        crowd_p[:, None, :]
        | (ga_p[:, None, :] < lo[None, :, None])
        | (ga_p[:, None, :] > hi[None, :, None])
        | ~gt_valid[:, None, :]
    )
    real = ~gt_ignore  # pads are never "real": forced ignored above
    thr = np.minimum(np.asarray(iou_thresholds, np.float64), 1 - 1e-10)  # (T,)

    det_matches = np.zeros((n_cells, num_areas, num_thrs, d_pad), bool)
    det_ignore = np.zeros((n_cells, num_areas, num_thrs, d_pad), bool)
    avail = np.broadcast_to(
        gt_valid[:, None, None, :], (n_cells, num_areas, num_thrs, g_pad)
    ).copy()
    g_idx = np.arange(g_pad)
    n_idx = np.arange(n_cells)[:, None, None]
    a_idx = np.arange(num_areas)[None, :, None]
    for d_i in range(d_pad):
        # pad detections (d_i >= a cell's true count) carry IoU -1 for every
        # gt, below any threshold — no per-iteration validity masking needed
        iou_row = ious_p[:, d_i, :]  # (N, G)
        cand = avail & (iou_row[:, None, None, :] >= thr[None, None, :, None])
        cand_real = cand & real[:, :, None, :]
        use_real = cand_real.any(axis=3)  # non-ignored gts take precedence
        pick_from = np.where(use_real[..., None], cand_real, cand & gt_ignore[:, :, None, :])
        has = pick_from.any(axis=3)  # (N, A, T)
        if not has.any():
            continue
        vals = np.where(pick_from, iou_row[:, None, None, :], -1.0)
        best_g = g_pad - 1 - np.argmax(vals[..., ::-1], axis=3)  # last-wins argmax
        det_matches[:, :, :, d_i] = has
        det_ignore[:, :, :, d_i] = has & gt_ignore[n_idx, a_idx, best_g]
        # crowd gts can absorb any number of detections: only non-crowd
        # picks claim their gt
        claimed = has & ~crowd_p[n_idx, best_g]
        avail &= ~(claimed[..., None] & (g_idx[None, None, None, :] == best_g[..., None]))

    # unmatched detections outside the area range are ignored
    det_out = (da_p[:, None, :] < lo[None, :, None]) | (da_p[:, None, :] > hi[None, :, None])
    det_ignore |= (~det_matches) & det_out[:, :, None, :] & det_valid[:, None, None, :]

    num_gt = (~gt_ignore).sum(axis=2)  # (N, A)
    return det_matches, det_ignore, sc_p, det_valid, num_gt


def _accumulate_cells(
    groups: List[Tuple[np.ndarray, Tuple]],
    num_thrs: int,
    rec_thresholds: np.ndarray,
    max_dets: Sequence[int],
    num_areas: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Batched :func:`_accumulate_class_area` over every (area, maxdet) cell
    of one class at once.

    ``groups`` pairs each bucket's original cell indices with its
    :func:`_match_cells_batched` output.  Per max-det cap the detections of
    ALL cells flatten into one score-sorted column set, shared across area
    ranges (scores do not depend on the area range); the flatten order is
    restored to global cell order first so stable-sort tie-breaking is
    bit-identical to concatenating per-cell arrays.

    Returns ``(precision (T, R, A, M), recall (T, A, M))``.
    """
    num_rec = len(rec_thresholds)
    n_m = len(max_dets)
    precision = -np.ones((num_thrs, num_rec, num_areas, n_m))
    recall = -np.ones((num_thrs, num_areas, n_m))
    if not groups:
        return precision, recall
    npig = np.zeros(num_areas, dtype=np.int64)
    for _cells_idx, (_dm, _dig, _sc, _dv, num_gt) in groups:
        npig += num_gt.sum(axis=0)

    eps = np.finfo(np.float64).eps
    single = len(groups) == 1  # one bucket: cell order is already global order
    for m_idx, m in enumerate(max_dets):
        if single:
            _ci, (dm_s, dig_s, sc_s, dv_s, _ng) = groups[0]
            valid_s = dv_s & (np.arange(dv_s.shape[1])[None, :] < m)
            # flat (cell * Dp) positions in cell-major order == the per-cell
            # concatenation order; one stable score sort gives the columns
            flat = np.flatnonzero(valid_s.ravel())
            scores = sc_s.ravel()[flat]
            cols = flat[np.argsort(-scores, kind="mergesort")]
        else:
            valids = []
            counts = []
            for cells_idx, (_dm, _dig, sc, dv, _ng) in groups:
                valid = dv & (np.arange(dv.shape[1])[None, :] < m)
                valids.append(valid)
                counts.append((cells_idx, valid.sum(axis=1)))
            # global column order = per-cell blocks in original cell order
            # (the per-cell concatenation order), then one stable score sort
            rows_cell = np.concatenate([np.repeat(ci, cnt) for ci, cnt in counts])
            perm = np.argsort(rows_cell, kind="stable")
            scores = np.concatenate(
                [sc[valid] for valid, (_ci, (_dm, _dig, sc, _dv, _ng)) in zip(valids, groups)]
            )[perm]
            cols = perm[np.argsort(-scores, kind="mergesort")]
        nd = cols.shape[0]
        for a_idx in range(num_areas):
            if npig[a_idx] == 0:
                continue
            if nd == 0:
                precision[:, :, a_idx, m_idx] = 0.0
                recall[:, a_idx, m_idx] = 0.0
                continue
            if single:
                matches = np.transpose(dm_s[:, a_idx], (1, 0, 2)).reshape(num_thrs, -1)[:, cols]
                ignore = np.transpose(dig_s[:, a_idx], (1, 0, 2)).reshape(num_thrs, -1)[:, cols]
            else:
                matches = np.concatenate(
                    [
                        np.transpose(dm[:, a_idx], (1, 0, 2))[:, valid]
                        for valid, (_ci, (dm, _dig, _sc, _dv, _ng)) in zip(valids, groups)
                    ],
                    axis=1,
                )[:, cols]
                ignore = np.concatenate(
                    [
                        np.transpose(dig[:, a_idx], (1, 0, 2))[:, valid]
                        for valid, (_ci, (_dm, dig, _sc, _dv, _ng)) in zip(valids, groups)
                    ],
                    axis=1,
                )[:, cols]
            tp_sum = np.cumsum(matches & ~ignore, axis=1).astype(np.float64)
            fp_sum = np.cumsum(~matches & ~ignore, axis=1).astype(np.float64)
            rc = tp_sum / npig[a_idx]
            pr = tp_sum / np.maximum(fp_sum + tp_sum, eps)
            recall[:, a_idx, m_idx] = rc[:, -1]
            # monotone precision envelope from the right (pycocotools loop)
            pr = np.maximum.accumulate(pr[:, ::-1], axis=1)[:, ::-1]
            for t_idx in range(num_thrs):
                inds = np.searchsorted(rc[t_idx], rec_thresholds, side="left")
                q = np.zeros(num_rec)
                valid_i = inds < nd
                q[valid_i] = pr[t_idx][inds[valid_i]]
                precision[t_idx, :, a_idx, m_idx] = q
    return precision, recall


def precompute_geometries(
    detections: Sequence[Tuple],
    groundtruths: Sequence[Tuple],
    iou_type: str,
) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Class-independent pairwise geometry, ONCE per image (intersections +
    areas); the per-class loop in :func:`coco_evaluate` only slices these.
    pycocotools recomputes IoU per (image, category) — for masks that means
    re-decoding RLEs K times; here each mask is decoded once and intersected
    by one matmul."""
    return [
        _pairwise_geometry(detections[img][0], groundtruths[img][0], iou_type)
        for img in range(len(detections))
    ]


def coco_evaluate(
    detections: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray]],
    groundtruths: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    iou_thresholds: Sequence[float],
    rec_thresholds: Sequence[float],
    max_detection_thresholds: Sequence[int],
    class_ids: Sequence[int],
    average: str = "macro",
    iou_type: str = "bbox",
    geom_cache: Optional[List[Tuple[np.ndarray, np.ndarray, np.ndarray]]] = None,
    extended: bool = False,
) -> Dict[str, np.ndarray]:
    """Full COCO evaluation over per-image detections/groundtruths.

    The hot path: per class, every image's cell is padded to a shared
    pow-2 (D, G) bucket, matched by ONE vectorized greedy pass
    (:func:`_match_cells_batched`) and accumulated by ONE batched
    precision/recall pass (:func:`_accumulate_cells`).  Bit-identical to
    the per-cell reference path (:func:`coco_evaluate_unfused`).

    Args:
        detections: per image (geometry, scores (D,), labels (D,)).
        groundtruths: per image (geometry, labels (G,), iscrowd (G,),
            area (G,) — zero entries fall back to the geometry area).
        iou_type: geometry kind — ``bbox`` (geometry = xyxy (N, 4) array) or
            ``segm`` (geometry = ``((h, w), [RLE runs per mask])``).
        class_ids: the class label space to evaluate.
        average: ``macro`` (per-class then averaged, COCO standard) or
            ``micro`` (all classes pooled into one).
        geom_cache: output of a prior :func:`precompute_geometries` call on
            the same inputs — lets a caller that evaluates twice (e.g. micro
            scores + macro per-class values) pay the mask-decode/intersection
            cost once.
    """
    iou_thrs = np.asarray(iou_thresholds, dtype=np.float64)
    rec_thrs = np.asarray(rec_thresholds, dtype=np.float64)
    max_dets = sorted(max_detection_thresholds)
    num_imgs = len(detections)

    # micro pools all classes into one evaluation bucket, but the reported
    # `classes` stay the observed ids
    eval_class_ids: Sequence[int] = [0] if average == "micro" else class_ids

    area_names = list(_AREA_RANGES)
    all_ranges = [_AREA_RANGES[a] for a in area_names]
    # precision[T, R, K, A, M], recall[T, K, A, M]
    precision = -np.ones((len(iou_thrs), len(rec_thrs), len(eval_class_ids), len(area_names), len(max_dets)))
    recall = -np.ones((len(iou_thrs), len(eval_class_ids), len(area_names), len(max_dets)))

    per_image_geom = (
        geom_cache if geom_cache is not None else precompute_geometries(detections, groundtruths, iou_type)
    )

    # class-independent work, ONCE per image (shared by every class and by
    # a micro+macro double evaluation): the full crowd-aware IoU matrix and
    # one stable score sort — a per-class stable subset selection of a
    # sorted order equals sorting the subset
    per_image_full = []
    for img in range(num_imgs):
        _, det_scores, _ = detections[img]
        _, _, gt_crowd, gt_area = groundtruths[img]
        inter_full, det_area_full, gt_area_geom_full = per_image_geom[img]
        union = det_area_full[:, None] + gt_area_geom_full[None, :] - inter_full
        union = np.where(gt_crowd[None, :].astype(bool), det_area_full[:, None], union)
        ious_full = inter_full / np.where(union > 0, union, 1.0)
        area_eff = np.where(gt_area > 0, gt_area, gt_area_geom_full)
        per_image_full.append((ious_full, np.argsort(-det_scores, kind="stable"), area_eff))

    iou_map: Dict[Tuple[int, int], np.ndarray] = {}
    for k_idx, class_id in enumerate(eval_class_ids):
        # per (image, class) cell: slice the presorted full-image pieces
        cells = []
        for img in range(num_imgs):
            _, det_scores, det_labels = detections[img]
            _, gt_labels, gt_crowd, _ = groundtruths[img]
            _, det_area_full, _ = per_image_geom[img]
            ious_full, order_full, area_eff = per_image_full[img]
            if average == "micro":
                idx = order_full[: max_dets[-1]]
                gt_sel = slice(None)
            else:
                idx = order_full[det_labels[order_full] == class_id][: max_dets[-1]]
                gt_sel = gt_labels == class_id
            ious = ious_full[idx][:, gt_sel]
            cells.append(
                (ious, det_area_full[idx], det_scores[idx], gt_crowd[gt_sel], area_eff[gt_sel])
            )
            if extended:
                iou_map[(img, int(class_id))] = ious

        groups = [
            (
                np.asarray(cell_idx, np.int64),
                _match_cells_batched(
                    [cells[i] for i in cell_idx], iou_thrs, all_ranges, max_dets[-1], d_pad, g_pad
                ),
            )
            for (d_pad, g_pad), cell_idx in _cell_buckets(
                cells, max_dets[-1], len(area_names), len(iou_thrs)
            ).items()
        ]
        prec_k, rec_k = _accumulate_cells(groups, len(iou_thrs), rec_thrs, max_dets, len(area_names))
        precision[:, :, k_idx] = prec_k
        recall[:, k_idx] = rec_k

    return _summarize(
        precision, recall, iou_thrs, class_ids, eval_class_ids, area_names, max_dets, iou_map, extended
    )


def coco_evaluate_unfused(
    detections: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray]],
    groundtruths: Sequence[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]],
    iou_thresholds: Sequence[float],
    rec_thresholds: Sequence[float],
    max_detection_thresholds: Sequence[int],
    class_ids: Sequence[int],
    average: str = "macro",
    iou_type: str = "bbox",
    geom_cache: Optional[List[Tuple[np.ndarray, np.ndarray, np.ndarray]]] = None,
    extended: bool = False,
) -> Dict[str, np.ndarray]:
    """The per-(image, class)-cell reference evaluation (pre-batching
    implementation, kept verbatim): the parity anchor the batched
    :func:`coco_evaluate` is asserted bit-identical against."""
    iou_thrs = np.asarray(iou_thresholds, dtype=np.float64)
    rec_thrs = np.asarray(rec_thresholds, dtype=np.float64)
    max_dets = sorted(max_detection_thresholds)
    num_imgs = len(detections)

    eval_class_ids: Sequence[int] = [0] if average == "micro" else class_ids

    area_names = list(_AREA_RANGES)
    precision = -np.ones((len(iou_thrs), len(rec_thrs), len(eval_class_ids), len(area_names), len(max_dets)))
    recall = -np.ones((len(iou_thrs), len(eval_class_ids), len(area_names), len(max_dets)))

    per_image_geom = (
        geom_cache if geom_cache is not None else precompute_geometries(detections, groundtruths, iou_type)
    )

    iou_map: Dict[Tuple[int, int], np.ndarray] = {}
    for k_idx, class_id in enumerate(eval_class_ids):
        # per (image, class): sort detections by score and compute IoUs ONCE,
        # shared across all four area ranges (pycocotools computes computeIoU
        # once per (img, cat) the same way)
        per_image_cls = []
        for img in range(num_imgs):
            _, det_scores, det_labels = detections[img]
            _, gt_labels, gt_crowd, gt_area = groundtruths[img]
            inter_full, det_area_full, gt_area_geom_full = per_image_geom[img]
            if average == "micro":
                det_sel = np.ones(det_labels.shape[0], dtype=bool)
                gt_sel = np.ones(gt_labels.shape[0], dtype=bool)
            else:
                det_sel = det_labels == class_id
                gt_sel = gt_labels == class_id
            area = gt_area[gt_sel]
            geom_area = gt_area_geom_full[gt_sel]
            area = np.where(area > 0, area, geom_area)
            ds, gc = det_scores[det_sel], gt_crowd[gt_sel]
            det_order = np.argsort(-ds, kind="stable")[: max_dets[-1]]
            ds = ds[det_order]
            da = det_area_full[det_sel][det_order]
            inter = inter_full[det_sel][:, gt_sel][det_order]
            union = da[:, None] + geom_area[None, :] - inter
            union = np.where(gc[None, :].astype(bool), da[:, None], union)
            ious = inter / np.where(union > 0, union, 1.0)
            per_image_cls.append((ious, da, ds, gc, area))
            if extended:
                iou_map[(img, int(class_id))] = ious

        # match once per image across ALL area ranges at the largest cap;
        # smaller caps reuse by slicing
        all_ranges = [_AREA_RANGES[a] for a in area_names]
        per_image_areas = [
            _match_image_areas(ious, da, ds, gc, ga, iou_thrs, all_ranges, max_dets[-1])
            for (ious, da, ds, gc, ga) in per_image_cls
        ]
        for a_idx in range(len(area_names)):
            results = [r if r is None else r[a_idx] for r in per_image_areas]
            for m_idx, max_det in enumerate(max_dets):
                prec, rec = _accumulate_class_area(results, len(iou_thrs), rec_thrs, max_det)
                precision[:, :, k_idx, a_idx, m_idx] = prec
                recall[:, k_idx, a_idx, m_idx] = rec

    return _summarize(
        precision, recall, iou_thrs, class_ids, eval_class_ids, area_names, max_dets, iou_map, extended
    )


def _summarize(
    precision: np.ndarray,
    recall: np.ndarray,
    iou_thrs: np.ndarray,
    class_ids: Sequence[int],
    eval_class_ids: Sequence[int],
    area_names: List[str],
    max_dets: List[int],
    iou_map: Dict[Tuple[int, int], np.ndarray],
    extended: bool,
) -> Dict[str, np.ndarray]:
    """Reduce the (T, R, K, A, M) precision / (T, K, A, M) recall tensors to
    the COCO summary scalars (shared by the batched and reference paths)."""

    def _map(thr_sel=slice(None), area="all", max_det_idx=-1, class_idx=None):
        a_idx = area_names.index(area)
        p = precision[thr_sel, :, :, a_idx, max_det_idx]
        if class_idx is not None:
            p = p[..., class_idx]
        p = p[p > -1]
        return np.float32(p.mean()) if p.size else np.float32(-1.0)

    def _mar(area="all", max_det_idx=-1, class_idx=None):
        a_idx = area_names.index(area)
        r = recall[:, :, a_idx, max_det_idx]
        if class_idx is not None:
            r = r[..., class_idx]
        r = r[r > -1]
        return np.float32(r.mean()) if r.size else np.float32(-1.0)

    thr50 = [i for i, t in enumerate(iou_thrs) if abs(t - 0.5) < 1e-9]
    thr75 = [i for i, t in enumerate(iou_thrs) if abs(t - 0.75) < 1e-9]

    out: Dict[str, np.ndarray] = {
        "map": _map(),
        "map_50": _map(thr_sel=thr50) if thr50 else np.float32(-1.0),
        "map_75": _map(thr_sel=thr75) if thr75 else np.float32(-1.0),
        "map_small": _map(area="small"),
        "map_medium": _map(area="medium"),
        "map_large": _map(area="large"),
        "mar_small": _mar(area="small"),
        "mar_medium": _mar(area="medium"),
        "mar_large": _mar(area="large"),
        "classes": np.asarray(class_ids, dtype=np.int32),
    }
    for m_idx, max_det in enumerate(max_dets):
        out[f"mar_{max_det}"] = _mar(max_det_idx=m_idx)
    out["map_per_class"] = np.asarray([_map(class_idx=k) for k in range(len(eval_class_ids))], np.float32)
    out["mar_per_class"] = np.asarray(
        [_mar(class_idx=k, max_det_idx=len(max_dets) - 1) for k in range(len(eval_class_ids))], np.float32
    )
    if extended:
        # the reference's extended_summary payload (reference mean_ap.py:525-536):
        # score-sorted per-(image, class) IoU matrices plus the raw
        # precision/recall tensors over (T, R, K, A, M) / (T, K, A, M)
        out["ious"] = iou_map
        out["precision"] = precision
        out["recall"] = recall
    return out
