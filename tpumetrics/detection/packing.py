"""Host-side packing of ragged detection batches into dense dict layout.

The packed update path of
:class:`~tpumetrics.detection.MeanAveragePrecision` takes each side of a
batch as ONE dict of ``(B, slots, ...)`` arrays plus a per-image ``count``
— the trace-safe fixed-shape form that streams through the bucketed
runtime.  This module is the boundary where ragged per-image inputs become
that form: plain numpy, pow-2 slot padding (the
:mod:`tpumetrics.runtime.bucketing` shape discipline, so the universe of
trace signatures stays bounded), zero device work — the arrays are handed
to ``submit()``/``update()`` which own device placement.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


from tpumetrics.runtime.bucketing import pow2_at_least as pow2_slots  # noqa: F401 — the slot-count bucketing


def pack_detection_batch(
    preds: Sequence[Dict],
    target: Sequence[Dict],
    det_slots: Optional[int] = None,
    gt_slots: Optional[int] = None,
) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray]]:
    """Pack list-of-dicts (bbox) inputs into the dense packed-dict pair.

    Args:
        preds: per image ``{"boxes" (D, 4), "scores" (D,), "labels" (D,)}``.
        target: per image ``{"boxes" (G, 4), "labels" (G,)}`` with optional
            ``iscrowd``/``area``.
        det_slots / gt_slots: fixed inner slot counts.  Default: the pow-2
            bucket of this batch's largest per-image count.  Streaming
            callers should pass a corpus-wide constant so every batch traces
            with the same inner shape (the leading image axis is bucketed by
            the runtime; the slot axes are bucketed HERE).

    Returns:
        ``(preds_dense, target_dense)`` numpy dicts: ``boxes (B, slots, 4)
        f32``, ``scores``/``labels`` ``(B, slots)``, optional
        ``iscrowd``/``area`` (emitted only when any input image carries
        them), and ``count (B,) i32``.
    """
    b = len(preds)
    if b != len(target):
        raise ValueError(f"preds describe {b} images but target {len(target)}")
    for side, items, required in (("preds", preds, ("boxes", "scores", "labels")),
                                  ("target", target, ("boxes", "labels"))):
        for i, item in enumerate(items):
            missing = [k for k in required if item.get(k) is None]
            if missing:
                raise ValueError(f"{side}[{i}] is missing required key(s) {missing}")
    nd = [int(np.shape(p["boxes"])[0]) if np.size(p["boxes"]) else 0 for p in preds]
    ng = [int(np.shape(t["boxes"])[0]) if np.size(t["boxes"]) else 0 for t in target]
    d_slots = pow2_slots(max(nd, default=0)) if det_slots is None else int(det_slots)
    g_slots = pow2_slots(max(ng, default=0)) if gt_slots is None else int(gt_slots)
    if max(nd, default=0) > d_slots or max(ng, default=0) > g_slots:
        raise ValueError(
            f"An image exceeds the slot budget: {max(nd, default=0)} dets / "
            f"{max(ng, default=0)} gts vs slots {d_slots}/{g_slots}"
        )
    for side, items in (("preds", preds), ("target", target)):
        for i, item in enumerate(items):
            labels = np.asarray(item["labels"])
            if labels.size and float(np.abs(labels).max()) > 2.0**24:
                raise ValueError(
                    f"{side}[{i}] labels exceed float32's exact-integer range "
                    "(2^24): distinct class ids would alias in the packed f32 "
                    "row layout.  Remap class ids below 2^24."
                )

    def fill(rows: List[int], items: Sequence[Dict], key: str, slots: int, dtype) -> np.ndarray:
        shape = (b, slots, 4) if key == "boxes" else (b, slots)
        out = np.zeros(shape, dtype)
        for i, item in enumerate(items):
            if rows[i] and item.get(key) is not None:
                val = np.asarray(item[key], dtype)
                out[i, : rows[i]] = val.reshape((rows[i], 4) if key == "boxes" else (rows[i],))
        return out

    preds_dense = {
        "boxes": fill(nd, preds, "boxes", d_slots, np.float32),
        "scores": fill(nd, preds, "scores", d_slots, np.float32),
        "labels": fill(nd, preds, "labels", d_slots, np.float32),
        "count": np.asarray(nd, np.int32),
    }
    target_dense = {
        "boxes": fill(ng, target, "boxes", g_slots, np.float32),
        "labels": fill(ng, target, "labels", g_slots, np.float32),
        "count": np.asarray(ng, np.int32),
    }
    if any(t.get("iscrowd") is not None for t in target):
        target_dense["iscrowd"] = fill(ng, target, "iscrowd", g_slots, np.float32)
    if any(t.get("area") is not None for t in target):
        target_dense["area"] = fill(ng, target, "area", g_slots, np.float32)
    return preds_dense, target_dense
