"""Detection metric domain (counterpart of reference ``detection/__init__.py``)."""

from tpumetrics.detection.ciou import CompleteIntersectionOverUnion
from tpumetrics.detection.diou import DistanceIntersectionOverUnion
from tpumetrics.detection.giou import GeneralizedIntersectionOverUnion
from tpumetrics.detection.iou import IntersectionOverUnion
from tpumetrics.detection.mean_ap import MeanAveragePrecision
from tpumetrics.detection.panoptic_qualities import ModifiedPanopticQuality, PanopticQuality

__all__ = [
    "CompleteIntersectionOverUnion",
    "DistanceIntersectionOverUnion",
    "GeneralizedIntersectionOverUnion",
    "IntersectionOverUnion",
    "MeanAveragePrecision",
    "ModifiedPanopticQuality",
    "PanopticQuality",
]
