"""Detection metric domain (counterpart of reference ``detection/__init__.py``)."""

from tpumetrics.detection.ciou import CompleteIntersectionOverUnion
from tpumetrics.detection.diou import DistanceIntersectionOverUnion
from tpumetrics.detection.giou import GeneralizedIntersectionOverUnion
from tpumetrics.detection.iou import IntersectionOverUnion
from tpumetrics.detection.mean_ap import MeanAveragePrecision
from tpumetrics.detection.packing import pack_detection_batch
from tpumetrics.detection.panoptic_qualities import ModifiedPanopticQuality, PanopticQuality

# NOTE: __all__ lists metric classes only (tests/detection/test_distributed
# keys its per-class DDP coverage off it); pack_detection_batch is public
# API but a helper, imported explicitly.
__all__ = [
    "CompleteIntersectionOverUnion",
    "DistanceIntersectionOverUnion",
    "GeneralizedIntersectionOverUnion",
    "IntersectionOverUnion",
    "MeanAveragePrecision",
    "ModifiedPanopticQuality",
    "PanopticQuality",
]
