"""CompleteIntersectionOverUnion (counterpart of reference ``detection/ciou.py``)."""

from __future__ import annotations

from typing import Callable

from tpumetrics.detection.iou import IntersectionOverUnion
from tpumetrics.functional.detection.ciou import _ciou_compute, _ciou_update


class CompleteIntersectionOverUnion(IntersectionOverUnion):
    """CIoU accumulated over batches (reference detection/ciou.py).

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.detection import CompleteIntersectionOverUnion
        >>> preds = [dict(boxes=jnp.asarray([[296.55, 93.96, 314.97, 152.79]]), labels=jnp.asarray([4]))]
        >>> target = [dict(boxes=jnp.asarray([[300.00, 100.00, 315.00, 150.00]]), labels=jnp.asarray([4]))]
        >>> metric = CompleteIntersectionOverUnion()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()["ciou"]), 4)
        0.6883
    """

    _iou_type: str = "ciou"
    _invalid_val: float = -2.0  # CIoU is bounded in [-2, 1] (reference ciou.py)

    _iou_update_fn: Callable = staticmethod(_ciou_update)
    _iou_compute_fn: Callable = staticmethod(_ciou_compute)
