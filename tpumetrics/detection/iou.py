"""IntersectionOverUnion (counterpart of reference ``detection/iou.py``)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from tpumetrics.detection.helpers import _fix_empty_tensors, _input_validator
from tpumetrics.functional.detection._box_ops import box_convert
from tpumetrics.functional.detection.iou import _iou_compute, _iou_update
from tpumetrics.metric import Metric
from tpumetrics.utils.data import dim_zero_cat

Array = jax.Array


class IntersectionOverUnion(Metric):
    """IoU between per-image detection and ground-truth boxes, accumulated
    over batches (reference detection/iou.py:30-291).

    Args:
        box_format: input box format.
        iou_threshold: entries below the threshold count as the invalid value.
        class_metrics: include per-class scores in the output.
        respect_labels: only compare boxes of matching labels.

    Example:
        >>> import jax.numpy as jnp
        >>> from tpumetrics.detection import IntersectionOverUnion
        >>> preds = [dict(boxes=jnp.asarray([[296.55, 93.96, 314.97, 152.79]]), labels=jnp.asarray([4]))]
        >>> target = [dict(boxes=jnp.asarray([[300.00, 100.00, 315.00, 150.00]]), labels=jnp.asarray([4]))]
        >>> metric = IntersectionOverUnion()
        >>> metric.update(preds, target)
        >>> round(float(metric.compute()["iou"]), 4)
        0.6898
    """

    is_differentiable: bool = False
    higher_is_better: bool = True
    full_state_update: bool = True

    _iou_type: str = "iou"
    _invalid_val: float = -1.0

    groundtruth_labels: List[Array]
    iou_matrix: List[Array]

    def __init__(
        self,
        box_format: str = "xyxy",
        iou_threshold: Optional[float] = None,
        class_metrics: bool = False,
        respect_labels: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_box_formats = ("xyxy", "xywh", "cxcywh")
        if box_format not in allowed_box_formats:
            raise ValueError(f"Expected argument `box_format` to be one of {allowed_box_formats} but got {box_format}")
        self.box_format = box_format
        self.iou_threshold = iou_threshold
        if not isinstance(class_metrics, bool):
            raise ValueError("Expected argument `class_metrics` to be a boolean")
        self.class_metrics = class_metrics
        if not isinstance(respect_labels, bool):
            raise ValueError("Expected argument `respect_labels` to be a boolean")
        self.respect_labels = respect_labels

        self.add_state("groundtruth_labels", default=[], dist_reduce_fx=None)
        self.add_state("iou_matrix", default=[], dist_reduce_fx=None)

    _iou_update_fn: Callable = staticmethod(_iou_update)
    _iou_compute_fn: Callable = staticmethod(_iou_compute)

    def update(self, preds: Sequence[Dict[str, Array]], target: Sequence[Dict[str, Array]]) -> None:
        """Accumulate per-image IoU matrices (reference detection/iou.py:142-160)."""
        _input_validator(preds, target, ignore_score=True)
        for p, t in zip(preds, target):
            det_boxes = self._get_safe_item_values(p["boxes"])
            gt_boxes = self._get_safe_item_values(t["boxes"])
            self.groundtruth_labels.append(jnp.asarray(t["labels"], jnp.int32).ravel())

            iou_matrix = type(self)._iou_update_fn(det_boxes, gt_boxes, self.iou_threshold, self._invalid_val)
            if self.respect_labels:
                label_eq = jnp.asarray(p["labels"]).reshape(-1, 1) == jnp.asarray(t["labels"]).reshape(1, -1)
                iou_matrix = jnp.where(label_eq, iou_matrix, self._invalid_val)
            self.iou_matrix.append(iou_matrix)

    def _get_safe_item_values(self, boxes: Array) -> Array:
        boxes = _fix_empty_tensors(jnp.asarray(boxes, jnp.float32))
        if boxes.size > 0:
            boxes = box_convert(boxes, in_fmt=self.box_format, out_fmt="xyxy")
        return boxes

    def compute(self) -> Dict[str, Array]:
        """Mean over valid matrix entries, plus optional per-class means."""
        valid_entries = [mat[mat != self._invalid_val] for mat in self.iou_matrix]
        all_entries = (
            jnp.concatenate([v.ravel() for v in valid_entries])
            if valid_entries
            else jnp.zeros((0,), jnp.float32)
        )
        score = all_entries.mean() if all_entries.size else jnp.zeros(())
        results: Dict[str, Array] = {f"{self._iou_type}": score}

        if self.class_metrics:
            gt_labels = dim_zero_cat(self.groundtruth_labels) if self.groundtruth_labels else jnp.zeros((0,))
            import numpy as np

            classes = sorted(np.unique(np.asarray(gt_labels)).astype(int).tolist()) if gt_labels.size else []
            for cl in classes:
                masked = []
                for mat, labels in zip(self.iou_matrix, self.groundtruth_labels):
                    class_mask = jnp.asarray(labels) == cl
                    sub = mat[:, class_mask]
                    masked.append(sub[sub != self._invalid_val].ravel())
                vals = jnp.concatenate(masked) if masked else jnp.zeros((0,))
                results[f"{self._iou_type}/cl_{cl}"] = vals.mean() if vals.size else jnp.zeros(())
        return results
