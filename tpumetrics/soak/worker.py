"""One soak rank: a subprocess driving a StreamingEvaluator under chaos.

Launched by the :mod:`~tpumetrics.soak.supervisor` (one process per rank,
every epoch), speaking a JSON-lines command protocol on stdin/stdout:

- ``{"cmd": "restore"}`` — adopt the newest consistent cut for THIS world
  via :meth:`~tpumetrics.runtime.evaluator.StreamingEvaluator.
  restore_elastic` (optionally quorum-degraded); replies with the adopted
  position and restore latency.
- ``{"cmd": "feed", "start": s, "stop": e, "base": b}`` — submit every
  stream index ``i`` in ``[s, e)`` with ``(i - b) % world == rank`` (the
  strided sharding the supervisor's oracle mirrors), flush, ack with the
  row count.
- ``{"cmd": "cut"}`` — one coordinated snapshot cut (barrier over the
  file wire; the supervisor issues this to every rank concurrently).
- ``{"cmd": "stats"}`` / ``{"cmd": "ping"}`` — observability/liveness.
- ``{"cmd": "abort"}`` — immediate ``os._exit`` (the supervisor tears the
  slice down after a SIGKILL incident, as a preempted fleet would).
- ``{"cmd": "exit"}`` — clean close (drain queue, no final cut) and exit.

SIGTERM is the *graceful preemption notice*: the installed
:func:`~tpumetrics.runtime.drain.install_preemption_handler` (raise mode)
interrupts the command loop, the evaluator drains — intake off, queue
applied, ONE final coordinated cut (every rank received the same notice, so
the cut barrier completes) — a flight-recorder dump is written, and the
process exits 0 with a typed ``{"event": "drained", ...}`` status line.
In-flight batches are never lost by a polite preemption; the supervisor
asserts exactly that.

Telemetry continuity: the global collective ledger streams to a per-rank
JSONL sink under ``<root>/telemetry/`` (the supervisor checks
``elastic_restore``/``elastic_degraded`` events against the schedule) and a
flight recorder rides ``<root>/flight/``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _println(obj: dict) -> None:
    sys.stdout.write(json.dumps(obj, sort_keys=True, default=repr) + "\n")
    sys.stdout.flush()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpumetrics.soak.worker")
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world", type=int, required=True)
    ap.add_argument("--epoch", type=int, required=True)
    ap.add_argument("--root", required=True, help="shared soak root directory")
    ap.add_argument("--traffic-seed", type=int, default=1)
    ap.add_argument("--num-classes", type=int, default=5)
    ap.add_argument("--max-rows", type=int, default=8)
    ap.add_argument("--keep-cuts", type=int, default=3)
    ap.add_argument("--barrier-timeout", type=float, default=90.0)
    ap.add_argument(
        "--fault-plan", default=None,
        help="JSON IOFault list (tpumetrics.soak.faults.FaultPlan.to_json) "
        "installed as the storage shim's fault injector at startup; the "
        "supervisor normally arms plans over the {'cmd': 'faults'} wire "
        "instead, so windows open and close at leg boundaries",
    )
    args = ap.parse_args(argv)

    # heavy imports AFTER arg parsing (a bad invocation fails fast)
    import jax.numpy as jnp  # noqa: F401  (forces backend init before traffic)

    from tpumetrics import telemetry
    from tpumetrics.resilience import QuorumPolicy, StorageError, SyncPolicy, set_sync_policy
    from tpumetrics.runtime import StreamingEvaluator, install_preemption_handler
    from tpumetrics.runtime.drain import PreemptionInterrupt
    from tpumetrics.soak.faults import FaultPlan
    from tpumetrics.soak.traffic import make_batch, make_metric
    from tpumetrics.soak.wire import FileBarrierBackend
    from tpumetrics.telemetry.export import enable_flight_recorder, flight_dump
    from tpumetrics.telemetry.sinks import JsonlSink

    rank, world, epoch = args.rank, args.world, args.epoch
    os.makedirs(os.path.join(args.root, "telemetry"), exist_ok=True)
    sink = JsonlSink(
        os.path.join(args.root, "telemetry", f"epoch{epoch:03d}-rank{rank:05d}.jsonl")
    )
    telemetry.get_ledger().add_sink(sink)
    telemetry.enable()  # the global ledger records only while enabled
    enable_flight_recorder(os.path.join(args.root, "flight"))

    # the cut barrier's deadline: the file wire's own poll backstop sits just
    # under the SyncPolicy watchdog so a dead peer surfaces as the wire's
    # named-rank error rather than a bare watchdog timeout
    set_sync_policy(SyncPolicy(timeout=args.barrier_timeout))
    backend = FileBarrierBackend(
        os.path.join(args.root, "wire", f"epoch-{epoch:03d}"),
        rank=rank, world_size=world, timeout=max(1.0, args.barrier_timeout - 5.0),
    )
    ev = StreamingEvaluator(
        make_metric(args.num_classes),
        buckets=int(args.max_rows),
        snapshot_dir=os.path.join(args.root, "snapshots"),
        snapshot_rank=rank,
        snapshot_world_size=world,
        barrier_backend=backend,
        keep_cuts=args.keep_cuts,
    )
    guard = install_preemption_handler(ev, mode="raise", final_cut=True)
    if args.fault_plan:
        FaultPlan.from_json(args.fault_plan).install()

    def _drain_and_exit(signum) -> int:
        t0 = time.perf_counter()
        reports = guard.drain_now()
        flight = flight_dump("preemption_drain", rank=rank, epoch=epoch)
        _println(
            {
                "event": "drained",
                "rank": rank,
                "signum": signum,
                "drain_s": time.perf_counter() - t0,
                "report": reports[0].to_dict(),
                "flight": flight,
            }
        )
        sink.flush()
        return 0

    def handle(cmd: dict) -> dict:
        name = cmd["cmd"]
        if name == "ping":
            return {"ok": True, "cmd": "ping", "rank": rank}
        if name == "restore":
            q = cmd.get("quorum_min_ranks")
            t0 = time.perf_counter()
            info = ev.restore_elastic(
                quorum=QuorumPolicy(min_ranks=int(q)) if q else None
            )
            wall = time.perf_counter() - t0
            return {"ok": True, "cmd": "restore", "restore": info, "wall_s": wall}
        if name == "feed":
            start, stop, base = int(cmd["start"]), int(cmd["stop"]), int(cmd["base"])
            rows = batches = 0
            for i in range(start, stop):
                if (i - base) % world != rank:
                    continue
                preds, target = make_batch(
                    args.traffic_seed, i,
                    num_classes=args.num_classes, max_rows=args.max_rows,
                )
                ev.submit(jnp.asarray(preds), jnp.asarray(target))
                rows += preds.shape[0]
                batches += 1
            ev.flush()
            return {"ok": True, "cmd": "feed", "batches": batches, "rows": rows}
        if name == "cut":
            # a StorageError here is the degradation contract, not a wedge:
            # the shim's retry budget is spent, the evaluator latched the
            # durability_degraded window and keeps serving from HBM — ack
            # the cut as ATTEMPTED (path None) so the supervisor tracks the
            # newest COMPLETE cut instead of aborting the leg
            try:
                path = ev.snapshot()
            except StorageError as err:
                return {
                    "ok": True, "cmd": "cut", "path": None,
                    "storage_error": f"{type(err).__name__}: {err}",
                    "batches": ev.stats()["batches"],
                }
            return {
                "ok": True, "cmd": "cut", "path": path,
                "batches": ev.stats()["batches"],
            }
        if name == "faults":
            # arm/disarm a seeded storage fault plan for the NEXT leg; the
            # shim's injector is process-global, so this window scopes every
            # durability write this worker performs
            plan = cmd.get("plan")
            if plan:
                FaultPlan.from_json(plan).install()
            else:
                FaultPlan.uninstall()
            return {"ok": True, "cmd": "faults", "armed": bool(plan)}
        if name == "stats":
            s = ev.stats()
            return {
                "ok": True, "cmd": "stats",
                "batches": s["batches"], "items": s["items"],
                "degraded": s["degraded"], "crashes": s["crashes"],
            }
        if name == "telemetry":
            # the federation payload: this rank's whole instruments registry
            # (sketch state included) + ledger counters, as plain JSON — the
            # supervisor merges every rank's into one live /metrics view
            from tpumetrics.telemetry.federate import local_snapshot

            return {"ok": True, "cmd": "telemetry", "snapshot": local_snapshot(rank=rank)}
        raise ValueError(f"unknown command {name!r}")

    _println({"event": "ready", "rank": rank, "world": world, "epoch": epoch, "pid": os.getpid()})
    try:
        while True:
            try:
                line = sys.stdin.readline()
            except PreemptionInterrupt as notice:
                return _drain_and_exit(notice.signum)
            if not line:  # EOF: the supervisor is gone — exit quietly
                ev.close(drain=False)
                return 0
            line = line.strip()
            if not line:
                continue
            cmd = json.loads(line)
            if cmd.get("cmd") == "abort":
                # slice teardown after a peer's SIGKILL: no drain, no cut
                _println({"event": "aborted", "rank": rank})
                sys.stdout.flush()
                os._exit(3)
            if cmd.get("cmd") == "exit":
                ev.close(drain=True)
                _println({"ok": True, "cmd": "exit"})
                sink.flush()
                return 0
            try:
                resp = handle(cmd)
            except PreemptionInterrupt as notice:
                return _drain_and_exit(notice.signum)
            except Exception as err:  # surface to the supervisor, typed
                resp = {
                    "ok": False, "cmd": cmd.get("cmd"),
                    "error": f"{type(err).__name__}: {err}",
                }
            # flush BEFORE the ack: the supervisor reads ledger continuity
            # the moment every ack arrives, so the ack must imply the events
            # are on disk
            sink.flush()
            _println(resp)
    except PreemptionInterrupt as notice:
        return _drain_and_exit(notice.signum)


if __name__ == "__main__":
    sys.exit(main())
