"""A host-object barrier channel over a shared directory.

The coordinated snapshot cut (:func:`tpumetrics.resilience.elastic.
snapshot_barrier`) needs exactly one wire primitive: ``all_gather_object``
of a small JSON-able stamp across every rank.  On a real fleet that rides
the DCN backend; on boxes whose jaxlib cannot run cross-process collectives
(the common CPU container), the chaos soak still needs REAL process
boundaries — so this backend implements the object gather over the one
transport every pool shares anyway: the snapshot filesystem.

Protocol: the barrier's ``n``-th invocation on every rank writes its stamp
atomically (temp + rename) to ``<dir>/round-<n>/stamp-<rank>.json``, then
polls until all ``world`` stamps exist and returns them in rank order.
Rounds are aligned by construction — every rank performs the same sequence
of coordinated cuts (the supervisor commands them in lockstep), and each
epoch gets a fresh wire directory, so round ``n`` on one rank can only ever
meet round ``n`` on a peer.

Failure semantics match the DCN wire: a rank that died before writing its
stamp stalls the poll until the deadline, which surfaces through the active
:class:`~tpumetrics.resilience.policy.SyncPolicy` as a typed timeout/
failure (the barrier runs under :func:`~tpumetrics.resilience.policy.
run_guarded`); the internal ``timeout`` here is a backstop for unguarded
use.  Stamps are single-use files: nothing is ever overwritten, so a
late-arriving reader can never observe a torn payload (rename is atomic).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, List, Optional

from tpumetrics.parallel.backend import DistributedBackend

__all__ = ["BarrierWireError", "FileBarrierBackend"]


class BarrierWireError(RuntimeError):
    """The file-wire barrier could not complete (deadline, unreadable stamp).

    Deliberately NOT a ``TPUMetricsUserError``: :func:`~tpumetrics.
    resilience.policy.run_guarded` treats user errors as deterministic
    (never retried) — a missing peer stamp is the transient/dead-peer
    class, the same classification a dropped DCN collective gets."""


class FileBarrierBackend(DistributedBackend):
    """``all_gather_object`` over a shared directory (module docstring).

    Args:
        directory: the wire directory, shared by every rank of the pool
            (one per epoch — a restored world must start a fresh round
            sequence).
        rank / world_size: this process's identity in the pool.
        timeout: internal poll deadline in seconds (backstop; the real
            deadline is the ambient :class:`SyncPolicy`).
        poll_interval: sleep between directory polls.
    """

    has_object_channel = True

    def __init__(
        self,
        directory: str,
        *,
        rank: int,
        world_size: int,
        timeout: float = 120.0,
        poll_interval: float = 0.005,
    ) -> None:
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        if not (0 <= int(rank) < int(world_size)):
            raise ValueError(f"rank must be in [0, {world_size}), got {rank}")
        self.directory = directory
        self._rank = int(rank)
        self._world = int(world_size)
        self._timeout = float(timeout)
        self._poll = float(poll_interval)
        self._round = 0

    # ------------------------------------------------------------- identity

    def available(self) -> bool:
        return True

    def world_size(self) -> int:
        return self._world

    def rank(self) -> int:
        return self._rank

    @property
    def rounds_completed(self) -> int:
        return self._round

    # ---------------------------------------------------------------- wire

    def all_gather_object(self, obj: Any, group: Optional[Any] = None) -> List[Any]:
        n = self._round
        self._round += 1
        rdir = os.path.join(self.directory, f"round-{n:06d}")
        os.makedirs(rdir, exist_ok=True)
        mine = os.path.join(rdir, f"stamp-{self._rank:05d}.json")
        fd, tmp = tempfile.mkstemp(prefix=".stamp-", suffix=".tmp", dir=rdir)
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(obj, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, mine)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

        paths = [os.path.join(rdir, f"stamp-{r:05d}.json") for r in range(self._world)]
        deadline = time.monotonic() + self._timeout
        while True:
            missing = [r for r, p in enumerate(paths) if not os.path.exists(p)]
            if not missing:
                break
            if time.monotonic() > deadline:
                raise BarrierWireError(
                    f"File-wire barrier round {n} timed out after {self._timeout}s: "
                    f"rank(s) {missing} never wrote a stamp under {rdir!r} — dead, "
                    "preempted, or not running the same barrier sequence."
                )
            time.sleep(self._poll)
        out: List[Any] = []
        for r, path in enumerate(paths):
            try:
                with open(path) as fh:
                    out.append(json.load(fh))
            except (OSError, json.JSONDecodeError) as err:
                raise BarrierWireError(
                    f"File-wire barrier round {n}: rank {r}'s stamp at {path!r} is "
                    f"unreadable ({err})."
                ) from err
        return out
