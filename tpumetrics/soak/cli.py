"""``python -m tpumetrics.soak`` — the chaos-soak CLI.

Three subcommands:

- ``generate`` — derive a deterministic schedule from a seed and write it
  as JSON (inspect it, check it into CI, replay a failure)::

      python -m tpumetrics.soak generate --seed 7 --world 3 --incidents 6 \\
          -o schedule.json

- ``run`` — execute a schedule (from a file, or generated inline from
  ``--seed``) over a real process pool rooted at ``--root``, writing the
  JSONL incident report (one line per incident, a ``summary`` line last)::

      python -m tpumetrics.soak run --schedule schedule.json \\
          --root /tmp/soak --out report.jsonl

- ``report`` — merge an existing soak's per-rank telemetry JSONL into one
  clock-aligned global timeline (:mod:`tpumetrics.telemetry.timeline`),
  print the cross-rank straggler summary, and optionally render the whole
  soak as a Perfetto/Chrome trace::

      python -m tpumetrics.soak report /tmp/soak --perfetto soak.trace.json

Exit status: 0 when every incident recovered and every gate held (for
``report``: when telemetry was found), 1 when any incident was
unrecovered, 2 for usage/schedule errors (or an empty/missing telemetry
directory).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Optional, Sequence

from tpumetrics.soak.schedule import ChaosSchedule, ScheduleError, generate_schedule
from tpumetrics.soak.supervisor import run_soak

__all__ = ["main"]


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tpumetrics.soak", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="command", required=True)

    gen = sub.add_parser("generate", help="derive a schedule from a seed")
    gen.add_argument("--seed", type=int, default=0)
    gen.add_argument("--world", type=int, default=3)
    gen.add_argument("--incidents", type=int, default=6)
    gen.add_argument("--min-world", type=int, default=2)
    gen.add_argument("--max-world", type=int, default=4)
    gen.add_argument("--feed-low", type=int, default=6)
    gen.add_argument("--feed-high", type=int, default=16)
    gen.add_argument("--cut-every", type=int, default=4)
    gen.add_argument(
        "--storage", action="store_true",
        help="lead the incident mix with the storage-fault kinds "
        "(corrupt_cut/disk_full/io_flaky); --incidents 3 is exactly the "
        "standing storage-fault gate",
    )
    gen.add_argument("-o", "--out", default="-", help="schedule JSON path ('-' = stdout)")

    run = sub.add_parser("run", help="execute a schedule over a real pool")
    src = run.add_mutually_exclusive_group(required=True)
    src.add_argument("--schedule", help="schedule JSON file (from 'generate')")
    src.add_argument("--seed", type=int, help="generate the schedule inline from this seed")
    run.add_argument("--world", type=int, default=3, help="initial world for --seed")
    run.add_argument("--incidents", type=int, default=6, help="incident count for --seed")
    run.add_argument(
        "--storage", action="store_true",
        help="with --seed: include the storage-fault incident kinds",
    )
    run.add_argument("--root", default=None, help="soak root dir (default: a fresh tempdir)")
    run.add_argument("--out", default=None, help="JSONL incident report path")
    run.add_argument("--verbose", action="store_true")

    rep = sub.add_parser(
        "report", help="merged cross-rank timeline + straggler summary"
    )
    rep.add_argument(
        "root",
        help="a soak root (its telemetry/ subdirectory) or a directory of "
        "per-rank epochNNN-rankNNNNN.jsonl files",
    )
    rep.add_argument(
        "--perfetto", default=None,
        help="also write the merged timeline as Chrome trace-event JSON here",
    )
    rep.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the straggler report as JSON instead of text",
    )
    return ap


def _report(args: argparse.Namespace) -> int:
    from tpumetrics.telemetry import timeline as _timeline

    try:
        candidates = [os.path.join(args.root, "telemetry"), args.root]
        streams = {}
        for directory in candidates:
            streams = _timeline.load_rank_streams(directory)
            if streams:
                break
        if not streams:
            print(
                f"error: no per-rank telemetry JSONL (epochNNN-rankNNNNN.jsonl) "
                f"under {candidates[0]} or {candidates[1]}",
                file=sys.stderr,
            )
            return 2
        merged = _timeline.merge_timelines(streams)
        report = _timeline.straggler_report(merged)
        if args.perfetto:
            _timeline.to_perfetto(merged, args.perfetto)
            print(f"perfetto trace written: {args.perfetto}", file=sys.stderr)
    except OSError as err:
        # the generate/run contract: I/O problems are clean usage errors
        # (exit 2), never a traceback
        print(f"error: {err}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(_timeline.render_report(merged, report))
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.command == "report":
        return _report(args)
    try:
        if args.command == "generate":
            schedule = generate_schedule(
                args.seed, world=args.world, n_incidents=args.incidents,
                min_world=args.min_world, max_world=args.max_world,
                feed_low=args.feed_low, feed_high=args.feed_high,
                cut_every=args.cut_every, storage=args.storage,
            )
            text = schedule.to_json()
            if args.out == "-":
                print(text)
            else:
                with open(args.out, "w") as fh:
                    fh.write(text + "\n")
            return 0

        if args.schedule is not None:
            with open(args.schedule) as fh:
                schedule = ChaosSchedule.from_json(fh.read())
        else:
            schedule = generate_schedule(
                args.seed, world=args.world, n_incidents=args.incidents,
                storage=args.storage,
            )
    except (ScheduleError, OSError) as err:
        print(f"error: {err}", file=sys.stderr)
        return 2

    root = args.root or tempfile.mkdtemp(prefix="tpumetrics-soak-")
    report = run_soak(schedule, root, out_jsonl=args.out, verbose=args.verbose)
    summary = {k: v for k, v in report.items() if k != "incidents"}
    print(json.dumps(summary, sort_keys=True))
    return 0 if report["unrecovered"] == 0 else 1
