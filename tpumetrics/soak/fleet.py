"""Fleet-layer chaos soak: seeded migrations, resizes, and SIGKILL
mid-migration, with standing zero-loss gates.

:mod:`~tpumetrics.soak.supervisor` gates the *rank* failure domain (real
subprocesses, real signals, coordinated cuts).  This runner gates the
*placement* failure domain on top of it: an in-process
:class:`~tpumetrics.fleet.FleetController` executes a
``generate_schedule(fleet=True)`` schedule — each leg feeds deterministic
traffic (:mod:`~tpumetrics.soak.traffic`), then performs one incident:

- ``migrate`` — a seeded tenant moves to a seeded target rank through the
  zero-loss two-phase handoff; with ``abrupt=True`` the whole pool is
  SIGKILLed mid-migration (after the cut — and, on a seeded coin, after
  the manifest committed), rebuilt cold on the same handoff root, and
  :meth:`~tpumetrics.fleet.FleetController.recover` must land the tenant
  on exactly one rank, chosen by the manifest state.
- ``resize`` — the pool grows or shrinks to ``world_after``, migrating
  every displaced tenant.

After EVERY incident the standing gates run: each tenant resident on
exactly one rank (the census agrees), ``compute()`` bit-identical to an
unmigrated single-service oracle over its full fed stream, and zero lost
or double-counted rows (the confusion-matrix total IS the row count, so
loss and double-count are both visible in one integer).  The report
carries the migration-latency p99 the ``fleet_resize`` bench ceiling
gates.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from tpumetrics.soak.schedule import ChaosSchedule, ScheduleError
from tpumetrics.soak.traffic import make_batch, make_metric, oracle_value, values_equal
from tpumetrics.utils.exceptions import TPUMetricsUserError

__all__ = ["FleetSoakError", "run_fleet_soak"]


class FleetSoakError(TPUMetricsUserError):
    """A standing fleet-soak gate failed (lost update, double residency,
    divergent compute, or an incident that did not recover)."""


def _tenant_seed(schedule: ChaosSchedule, idx: int) -> int:
    # disjoint per-tenant streams derived from the schedule's traffic seed
    return int(schedule.traffic_seed) * 1000 + 101 * idx


def _quantile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    pos = min(len(ordered) - 1, int(q * len(ordered)))
    return ordered[pos]


def run_fleet_soak(
    schedule: ChaosSchedule,
    *,
    tenants: int = 4,
    handoff_dir: Optional[str] = None,
    register_kw: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Execute a ``fleet=True`` chaos schedule and return the gate report
    (module docstring).  Raises :class:`FleetSoakError` on the first gate
    violation — the gates are the point, not the report."""
    if tenants < 1:
        raise ScheduleError(f"tenants must be >= 1, got {tenants}")
    for inc in schedule.incidents:
        if inc.kind not in ("migrate", "resize"):
            raise ScheduleError(
                f"run_fleet_soak executes fleet schedules only; got {inc.kind!r} "
                "(use generate_schedule(fleet=True))"
            )
    from tpumetrics.fleet import FleetController

    tids = [f"ft-{i}" for i in range(tenants)]
    seeds = {tid: _tenant_seed(schedule, i) for i, tid in enumerate(tids)}
    fed: Dict[str, int] = {tid: 0 for tid in tids}

    def factory(tid: str) -> Any:
        return make_metric(schedule.num_classes)

    def build(ranks: int) -> FleetController:
        return FleetController(
            factory, ranks=ranks, handoff_dir=handoff_dir,
            register_kw=dict(register_kw or {}),
        )

    fc = build(schedule.world)
    latencies: List[float] = []
    incident_log: List[Dict[str, Any]] = []
    lost_updates = 0
    try:
        for tid in tids:
            fc.register(tid)
        for leg, inc in enumerate(schedule.incidents):
            rng = random.Random(int(schedule.seed) * 100003 + leg)
            for _ in range(inc.feed):
                tid = rng.choice(tids)
                fc.submit(
                    tid,
                    *make_batch(
                        seeds[tid], fed[tid],
                        num_classes=schedule.num_classes,
                        max_rows=schedule.max_rows,
                    ),
                )
                fed[tid] += 1
            entry: Dict[str, Any] = {"leg": leg, "kind": inc.kind, "abrupt": inc.abrupt}
            if inc.kind == "resize":
                reports = fc.resize(inc.world_after)
                if fc.world != inc.world_after:
                    raise FleetSoakError(
                        f"leg {leg}: resize targeted {inc.world_after} ranks, "
                        f"fleet has {fc.world}"
                    )
                latencies.extend(r.latency_ms for r in reports)
                entry.update(world=fc.world, moved=len(reports))
            else:
                tid = inc.tenant or rng.choice(tids)
                ranks = fc.ranks
                source = next(r for r in ranks if tid in fc.service(r).tenant_ids())
                if inc.target_rank is not None:
                    target = inc.target_rank
                else:
                    others = [r for r in ranks if r != source]
                    target = rng.choice(others) if others else source
                if inc.abrupt:
                    fc = _sigkill_mid_migration(
                        fc, build, schedule, tid, source, target,
                        commit_first=rng.random() < 0.5,
                        tids=tids, seeds=seeds, fed=fed,
                    )
                    entry.update(tenant=tid, source=source, target=target,
                                 recovered=True)
                else:
                    report = fc.migrate(tid, target)
                    if report is not None:
                        latencies.append(report.latency_ms)
                    entry.update(tenant=tid, source=source, target=target)
            # ---- standing gates, after EVERY incident
            census = fc.census()
            for tid in tids:
                homes = [r for r in fc.ranks if tid in fc.service(r).tenant_ids()]
                if len(homes) != 1:
                    raise FleetSoakError(
                        f"leg {leg}: tenant {tid!r} resident on ranks {homes} "
                        "(exactly-once violated)"
                    )
                if census[tid]["owner_rank"] != homes[0]:
                    raise FleetSoakError(
                        f"leg {leg}: census says rank {census[tid]['owner_rank']} "
                        f"for {tid!r} but it lives on {homes[0]}"
                    )
                got = fc.compute(tid)
                want = oracle_value(
                    seeds[tid], range(fed[tid]),
                    num_classes=schedule.num_classes,
                    max_rows=schedule.max_rows,
                )
                lost = int(want["confmat"].sum()) - int(got["confmat"].sum())
                if lost:
                    lost_updates += abs(lost)
                    raise FleetSoakError(
                        f"leg {leg}: tenant {tid!r} {'lost' if lost > 0 else 'double-counted'} "
                        f"{abs(lost)} rows"
                    )
                if not values_equal(got, want):
                    raise FleetSoakError(
                        f"leg {leg}: tenant {tid!r} compute() diverged from the "
                        "unmigrated oracle"
                    )
            incident_log.append(entry)
        return {
            "seed": schedule.seed,
            "legs": len(schedule.incidents),
            "tenants": tenants,
            "world": fc.world,
            "routing_epoch": fc.ring.epoch,
            "bit_identical": True,
            "exactly_once": True,
            "lost_updates": lost_updates,
            "migrations": len(latencies),
            "migration_latency_p99_ms": _quantile(latencies, 0.99),
            "migration_latency_p50_ms": _quantile(latencies, 0.50),
            "incidents": incident_log,
        }
    finally:
        fc.close(drain=False)


def _sigkill_mid_migration(
    fc: Any,
    build: Any,
    schedule: ChaosSchedule,
    tid: str,
    source: int,
    target: int,
    *,
    commit_first: bool,
    tids: List[str],
    seeds: Dict[str, int],
    fed: Dict[str, int],
) -> Any:
    """Kill the pool mid-migration and recover it from the handoff root.

    The kill lands at one of the two durable states the manifest can hold:
    after the final cut (``commit_first=False`` — the migration never
    happened, the tenant recovers on the SOURCE) or after the manifest
    committed (``commit_first=True`` — it already did, recover on the
    TARGET).  The rebuilt pool re-registers and deterministically replays
    every OTHER tenant (standing in for their own snapshot recovery, which
    the rank soak gates); the victim must come back from the cut alone,
    batch count intact."""
    src = fc.service(source)
    mode, cut, meta = src.begin_migration(tid)
    if mode == "live":
        fc.handoff.cut(tid, cut, meta, mode=mode, source_rank=source,
                       target_rank=target)
    else:
        fc.handoff.cut_file(tid, cut, meta, source_rank=source,
                            target_rank=target)
    if commit_first and target != source:
        # the crash lands between the manifest flip and the ring/source
        # bookkeeping — the worst window: only the manifest state survives
        # to arbitrate ownership
        fc.handoff.mark_committed(tid)
    world = fc.world
    fc.close(drain=False)  # SIGKILL: every rank's memory is gone

    fc = build(world)
    for other in tids:
        if other == tid:
            continue
        fc.register(other)
        for i in range(fed[other]):
            fc.submit(
                other,
                *make_batch(
                    seeds[other], i,
                    num_classes=schedule.num_classes,
                    max_rows=schedule.max_rows,
                ),
            )
    reports = fc.recover()
    mine = [r for r in reports if r.tenant == tid]
    if len(mine) != 1:
        raise FleetSoakError(
            f"SIGKILL recovery produced {len(mine)} reports for {tid!r}, "
            "expected exactly one"
        )
    expect = target if (commit_first and target != source) else source
    if mine[0].extra.get("owner_rank") != expect:
        raise FleetSoakError(
            f"{tid!r} recovered on rank {mine[0].extra.get('owner_rank')}, "
            f"manifest state demands {expect}"
        )
    return fc
