"""Entry point for ``python -m tpumetrics.soak`` (see soak/cli.py)."""

import sys

from tpumetrics.soak.cli import main

if __name__ == "__main__":
    sys.exit(main())
