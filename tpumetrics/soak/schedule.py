"""Deterministic, seeded chaos schedules.

A schedule is a list of :class:`Incident`\\ s executed in order by the
supervisor.  One incident = one *leg* of traffic (``feed`` batches through
the current world, coordinated cuts every ``cut_every`` batches) followed by
one induced failure and one recovery+verification cycle:

- ``"sigterm"`` — polite preemption of the whole job: every rank receives
  SIGTERM, drains gracefully (intake off → queue applied → one final
  coordinated cut → typed exit), and the restore must cover EVERY fed
  batch — a polite preemption loses nothing.
- ``"sigkill"`` — abrupt death: ``tail`` batches are fed *after* the last
  cut (so the kill lands at an arbitrary point of the stream, not at a cut
  boundary), then the victim rank is SIGKILLed and the remaining ranks'
  slice is torn down.  Recovery restores the last complete cut; the tail is
  re-fed — the exactly-once gate.  With ``lose_member`` the victim's newest
  cut member is destroyed too (the killed-between-rename-and-replication
  failure mode), forcing an explicit quorum-degraded restore whose expected
  value the supervisor still predicts exactly.
- ``"shrink"`` / ``"grow"`` — world resize (``world_after`` differs), via
  graceful drain or abruptly (``abrupt=True`` rides the sigkill mechanism).

Determinism is load-bearing: :func:`generate_schedule` derives everything
from one seed via :class:`random.Random`, so a failing soak replays
bit-identically from its seed, and the pytest/bench gates pin known-good
seeds.  :func:`ChaosSchedule.to_json`/:func:`~ChaosSchedule.from_json`
round-trip the schedule for the ``python -m tpumetrics.soak`` CLI.
"""

from __future__ import annotations

import json
import random
from dataclasses import asdict, dataclass, replace
from typing import Any, Dict, Optional, Tuple

from tpumetrics.utils.exceptions import TPUMetricsUserError

__all__ = ["ChaosSchedule", "Incident", "ScheduleError", "generate_schedule"]

KINDS = ("sigkill", "sigterm", "shrink", "grow")
# fleet-layer incidents (tpumetrics.soak.fleet runner): a zero-loss tenant
# migration ("migrate", abrupt=True SIGKILLs the pool mid-migration and
# recovers from the handoff manifest) and an SLO-style pool resize
# ("resize", world_after != world).  Kept out of KINDS so pinned legacy
# seeds stay byte-identical; generate_schedule(fleet=True) opts in.
FLEET_KINDS = ("migrate", "resize")
# storage-fault incidents (the resilience/storage.py shim's standing gate),
# opted in via generate_schedule(storage=True) for the same pinned-seed
# reason.  All keep the world size: the failure is the DISK, not the fleet.
# - "io_flaky":    seeded transient EIO/stalls armed in every worker for the
#   leg — the shim's retries must absorb all of it (io_retry events, zero
#   loss, every cut complete).
# - "disk_full":   a bounded ENOSPC window — cut saves fail permanently,
#   the evaluator latches durability degradation and KEEPS SERVING; after
#   the window a heal cut must succeed and durability must resume.
# - "corrupt_cut": the newest cut member of a seeded rank (``target_rank``)
#   is corrupted on disk after an abrupt teardown — restore must fall back
#   (depth <= keep_cuts), quarantine the member, and re-feed exactly-once.
STORAGE_KINDS = ("corrupt_cut", "disk_full", "io_flaky")


class ScheduleError(TPUMetricsUserError):
    """A chaos schedule is malformed (unknown kind, illegal world size,
    tail exceeding the leg, victim outside the world)."""


@dataclass(frozen=True)
class Incident:
    """One leg of traffic plus one induced failure (module docstring)."""

    kind: str
    feed: int  # batches fed across the world during this leg
    world_after: int  # world size of the NEXT leg
    abrupt: bool = False  # SIGKILL mechanism (always True for kind="sigkill")
    target_rank: Optional[int] = None  # victim rank for abrupt incidents
    tail: int = 0  # batches fed after the last cut (lost by an abrupt kill)
    lose_member: bool = False  # destroy the victim's newest cut member too
    tenant: Optional[str] = None  # migration subject (fleet kinds; None = seeded)

    def validate(self, world_before: int, min_world: int = 1) -> None:
        if self.kind not in KINDS + FLEET_KINDS + STORAGE_KINDS:
            raise ScheduleError(
                f"Unknown incident kind {self.kind!r}; expected one of "
                f"{KINDS + FLEET_KINDS + STORAGE_KINDS}"
            )
        if self.feed < 1:
            raise ScheduleError(f"{self.kind}: feed must be >= 1, got {self.feed}")
        if self.kind in FLEET_KINDS:
            self._validate_fleet(world_before, min_world)
            return
        if self.kind in STORAGE_KINDS:
            self._validate_storage(world_before)
            return
        if self.world_after < max(1, min_world):
            raise ScheduleError(
                f"{self.kind}: world_after must be >= {max(1, min_world)}, got {self.world_after}"
            )
        if self.kind == "shrink" and not self.world_after < world_before:
            raise ScheduleError(
                f"shrink must reduce the world ({world_before} -> {self.world_after})"
            )
        if self.kind == "grow" and not self.world_after > world_before:
            raise ScheduleError(
                f"grow must enlarge the world ({world_before} -> {self.world_after})"
            )
        if self.kind == "sigterm" and self.abrupt:
            raise ScheduleError("sigterm is the graceful mechanism; use sigkill for abrupt")
        if self.kind == "sigkill" and not self.abrupt:
            raise ScheduleError("sigkill incidents must set abrupt=True")
        if self.abrupt:
            if self.target_rank is None or not (0 <= self.target_rank < world_before):
                raise ScheduleError(
                    f"{self.kind}: abrupt incidents need target_rank in [0, {world_before}), "
                    f"got {self.target_rank}"
                )
            if not (0 <= self.tail < self.feed):
                raise ScheduleError(
                    f"{self.kind}: tail must be in [0, feed), got tail={self.tail} feed={self.feed}"
                )
            if self.lose_member and self.target_rank == 0:
                # rank 0 carries the whole resharded prefix (sum states land
                # rank0 + zeros): losing its member would lose the entire
                # history, which is a different scenario than "one rank's
                # leg went missing" — keep the expected-value math honest
                raise ScheduleError("lose_member incidents need target_rank >= 1")
        else:
            if self.tail:
                raise ScheduleError(f"{self.kind}: graceful incidents drain everything (tail=0)")
            if self.lose_member:
                raise ScheduleError("lose_member needs an abrupt incident")

    def _validate_storage(self, world_before: int) -> None:
        # the disk fails, not the fleet: the world never resizes, nothing is
        # permanently lost (tail/lose_member are the abrupt-kill knobs), and
        # only corrupt_cut needs a victim (whose cut MEMBER is corrupted —
        # the process itself is torn down with the rest of the slice)
        if self.world_after != world_before:
            raise ScheduleError(
                f"{self.kind} must keep the world "
                f"({world_before} -> {self.world_after})"
            )
        if self.tail or self.lose_member:
            raise ScheduleError(f"{self.kind}: storage incidents take no tail/lose_member")
        if self.kind == "corrupt_cut":
            if not self.abrupt:
                raise ScheduleError(
                    "corrupt_cut must be abrupt: corruption is only observable "
                    "by a world that restores, not one that keeps its HBM state"
                )
            if self.target_rank is None or not (0 <= self.target_rank < world_before):
                raise ScheduleError(
                    f"corrupt_cut: target_rank (the rank whose cut member is "
                    f"corrupted) must be in [0, {world_before}), got {self.target_rank}"
                )
        else:
            if self.abrupt:
                raise ScheduleError(
                    f"{self.kind} recovers gracefully (the shim/degradation "
                    "latch is the mechanism under test, not an abrupt kill)"
                )
            if self.target_rank is not None:
                raise ScheduleError(f"{self.kind} takes no target_rank")

    def _validate_fleet(self, world_before: int, min_world: int) -> None:
        # the fleet runner's kill point is mid-MIGRATION (between cut and
        # commit), not mid-stream, so tail/lose_member don't apply; the
        # manifest is the single durable artifact being exercised
        if self.tail or self.lose_member:
            raise ScheduleError(
                f"{self.kind}: fleet incidents take no tail/lose_member"
            )
        if self.kind == "migrate":
            if self.world_after != world_before:
                raise ScheduleError(
                    f"migrate must keep the world ({world_before} -> {self.world_after})"
                )
            if self.target_rank is not None and not (
                0 <= self.target_rank < world_before
            ):
                raise ScheduleError(
                    f"migrate: target_rank must be in [0, {world_before}) or None, "
                    f"got {self.target_rank}"
                )
        else:  # resize
            if self.world_after == world_before:
                raise ScheduleError(
                    f"resize must change the world (stayed {world_before})"
                )
            if self.world_after < max(1, min_world):
                raise ScheduleError(
                    f"resize: world_after must be >= {max(1, min_world)}, "
                    f"got {self.world_after}"
                )
            if self.abrupt:
                raise ScheduleError(
                    "resize is always graceful; SIGKILL coverage rides "
                    "abrupt migrate incidents"
                )
            if self.target_rank is not None or self.tenant is not None:
                raise ScheduleError("resize takes no target_rank/tenant")


@dataclass(frozen=True)
class ChaosSchedule:
    """A full soak: initial world, incident list, cadences, gates."""

    seed: int
    world: int
    incidents: Tuple[Incident, ...]
    cut_every: int = 4  # coordinated-cut cadence in batches
    num_classes: int = 5  # traffic/metric shape
    max_rows: int = 8  # rows per batch in [1, max_rows]; also the bucket cap
    traffic_seed: int = 1
    keep_cuts: int = 3  # cut-level retention during the soak
    restore_ceiling_s: float = 60.0  # per-cycle restore latency gate
    barrier_timeout_s: float = 90.0  # file-wire + SyncPolicy deadline

    def __post_init__(self) -> None:
        if self.world < 1:
            raise ScheduleError(f"world must be >= 1, got {self.world}")
        if self.cut_every < 1:
            raise ScheduleError(f"cut_every must be >= 1, got {self.cut_every}")
        world = self.world
        for inc in self.incidents:
            inc.validate(world)
            world = inc.world_after

    @property
    def worlds(self) -> Tuple[int, ...]:
        """World-size trajectory, initial world first."""
        out = [self.world]
        for inc in self.incidents:
            out.append(inc.world_after)
        return tuple(out)

    def with_(self, **kwargs: Any) -> "ChaosSchedule":
        return replace(self, **kwargs)

    # ------------------------------------------------------------ round trip

    def to_dict(self) -> Dict[str, Any]:
        out = asdict(self)
        out["incidents"] = [asdict(i) for i in self.incidents]
        return out

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "ChaosSchedule":
        data = dict(data)
        incidents = tuple(Incident(**i) for i in data.pop("incidents", ()))
        return cls(incidents=incidents, **data)

    @classmethod
    def from_json(cls, text: str) -> "ChaosSchedule":
        try:
            return cls.from_dict(json.loads(text))
        except (TypeError, KeyError, json.JSONDecodeError) as err:
            raise ScheduleError(f"Unreadable schedule: {err}") from err


def generate_schedule(
    seed: int = 0,
    *,
    world: int = 3,
    n_incidents: int = 6,
    min_world: int = 2,
    max_world: int = 4,
    feed_low: int = 6,
    feed_high: int = 16,
    cut_every: int = 4,
    fleet: bool = False,
    storage: bool = False,
    **schedule_kwargs: Any,
) -> ChaosSchedule:
    """Derive a legal chaos schedule from one seed.

    Guarantees (for ``n_incidents >= 4``): at least one SIGKILL, one SIGTERM
    graceful drain, one shrink and one grow — the acceptance mix — placed in
    seeded order; remaining slots draw random kinds.  World sizes stay in
    ``[min_world, max_world]`` throughout; every abrupt incident gets a
    seeded victim and a seeded post-cut ``tail`` so kills land at arbitrary
    stream points.  Same seed → byte-identical schedule.

    ``fleet=True`` switches to the fleet-layer mix (``FLEET_KINDS``, run by
    :func:`tpumetrics.soak.fleet.run_fleet_soak`): migrations and pool
    resizes, guaranteeing (for ``n_incidents >= 3``) at least one ABRUPT
    migrate (SIGKILL mid-migration), one grow and one shrink.  The flag is
    an explicit opt-in precisely so ``fleet=False`` schedules stay
    byte-identical to every pinned pre-fleet seed.

    ``storage=True`` (same opt-in contract) ADDS the ``STORAGE_KINDS`` to
    the mix AND puts them first in the required set: all three storage
    incidents are guaranteed once ``n_incidents >= 3``, and storage legs
    are stretched to at least
    ``3 * cut_every`` batches so every seeded fault window provably
    overlaps real cut writes and a corrupt-cut restore always has an older
    complete cut to fall back to.
    """
    if n_incidents < 1:
        raise ScheduleError(f"n_incidents must be >= 1, got {n_incidents}")
    if not (1 <= min_world <= world <= max_world):
        raise ScheduleError(
            f"need 1 <= min_world <= world <= max_world, got {min_world}/{world}/{max_world}"
        )
    if fleet:
        return _generate_fleet_schedule(
            seed, world=world, n_incidents=n_incidents, min_world=min_world,
            max_world=max_world, feed_low=feed_low, feed_high=feed_high,
            cut_every=cut_every, **schedule_kwargs,
        )
    rng = random.Random(seed)
    pool = KINDS + STORAGE_KINDS if storage else KINDS
    # the required mix leads with the storage kinds when they are opted in:
    # a short storage soak (n_incidents == 3) is exactly the standing
    # storage-fault gate, not a lottery ticket
    required = (
        list(STORAGE_KINDS + KINDS)[:n_incidents] if storage
        else list(KINDS)[:n_incidents]
    )
    rng.shuffle(required)
    kinds = required + [rng.choice(pool) for _ in range(n_incidents - len(required))]

    incidents = []
    cur = world
    for kind in kinds:
        if kind in STORAGE_KINDS:
            # long enough for >= 3 cuts: every seeded fault window (after
            # <= 2) lands on a real cut write, and corrupt_cut always has
            # an in-leg predecessor cut to fall back to
            feed = rng.randint(
                max(feed_low, 3 * cut_every), max(feed_high, 3 * cut_every + 1)
            )
            if kind == "corrupt_cut":
                inc = Incident(
                    kind=kind, feed=feed, world_after=cur, abrupt=True,
                    target_rank=rng.randrange(cur),
                )
            else:
                inc = Incident(kind=kind, feed=feed, world_after=cur)
            incidents.append(inc)
            continue
        # keep every slot legal for the CURRENT world (random extras may
        # land on a world already at a bound; required kinds are placed
        # first, while both directions are still reachable)
        if kind == "shrink" and cur <= min_world:
            kind = "grow" if cur < max_world else "sigterm"
        if kind == "grow" and cur >= max_world:
            kind = "shrink" if cur > min_world else "sigterm"
        feed = rng.randint(feed_low, feed_high)
        if kind == "sigterm":
            inc = Incident(kind="sigterm", feed=feed, world_after=cur)
        elif kind == "sigkill":
            lose = cur >= 2 and rng.random() < 0.34
            target = rng.randrange(1, cur) if lose else rng.randrange(cur)
            inc = Incident(
                kind="sigkill", feed=feed, world_after=cur, abrupt=True,
                target_rank=target, tail=rng.randint(1, max(1, cut_every - 1)),
                lose_member=lose,
            )
        else:
            world_after = (
                rng.randint(min_world, cur - 1) if kind == "shrink"
                else rng.randint(cur + 1, max_world)
            )
            abrupt = rng.random() < 0.5
            if abrupt:
                lose = cur >= 2 and rng.random() < 0.25
                target = rng.randrange(1, cur) if lose else rng.randrange(cur)
                inc = Incident(
                    kind=kind, feed=feed, world_after=world_after, abrupt=True,
                    target_rank=target, tail=rng.randint(1, max(1, cut_every - 1)),
                    lose_member=lose,
                )
            else:
                inc = Incident(kind=kind, feed=feed, world_after=world_after)
        incidents.append(inc)
        cur = inc.world_after

    return ChaosSchedule(
        seed=seed, world=world, incidents=tuple(incidents), cut_every=cut_every,
        **schedule_kwargs,
    )


def _generate_fleet_schedule(
    seed: int,
    *,
    world: int,
    n_incidents: int,
    min_world: int,
    max_world: int,
    feed_low: int,
    feed_high: int,
    cut_every: int,
    **schedule_kwargs: Any,
) -> ChaosSchedule:
    """The ``fleet=True`` arm of :func:`generate_schedule`: seeded
    ``migrate``/``resize`` legs, with the acceptance trio (abrupt migrate,
    grow, shrink) guaranteed once ``n_incidents >= 3``.  Tenants and
    migration targets stay ``None`` here — the fleet runner derives both
    from the same seed, so they track the live world at execution time."""
    rng = random.Random(seed)
    required = ["migrate", "resize", "resize"][:n_incidents]
    rng.shuffle(required)
    kinds = required + [
        rng.choice(FLEET_KINDS) for _ in range(n_incidents - len(required))
    ]
    # force the guaranteed trio: the required "migrate" slot is abrupt
    # (SIGKILL mid-migration), the two required resizes go opposite ways
    force_abrupt = {kinds.index("migrate")} if "migrate" in kinds else set()
    resize_dirs = []  # seeded grow/shrink balance for the required resizes
    incidents = []
    cur = world
    for idx, kind in enumerate(kinds):
        feed = rng.randint(feed_low, feed_high)
        if kind == "migrate":
            abrupt = idx in force_abrupt or rng.random() < 0.34
            incidents.append(
                Incident(kind="migrate", feed=feed, world_after=cur, abrupt=abrupt)
            )
        else:
            grow_ok, shrink_ok = cur < max_world, cur > min_world
            if not resize_dirs and grow_ok and shrink_ok:
                want_grow = rng.random() < 0.5
            else:
                # alternate the forced directions, bounded by legality
                want_grow = grow_ok and (not shrink_ok or "grow" not in resize_dirs)
            if not grow_ok and not shrink_ok:  # min==max: degrade to migrate
                incidents.append(
                    Incident(kind="migrate", feed=feed, world_after=cur, abrupt=True)
                )
                cur = incidents[-1].world_after
                continue
            world_after = (
                rng.randint(cur + 1, max_world) if want_grow
                else rng.randint(min_world, cur - 1)
            )
            resize_dirs.append("grow" if want_grow else "shrink")
            incidents.append(
                Incident(kind="resize", feed=feed, world_after=world_after)
            )
        cur = incidents[-1].world_after
    return ChaosSchedule(
        seed=seed, world=world, incidents=tuple(incidents), cut_every=cut_every,
        **schedule_kwargs,
    )
