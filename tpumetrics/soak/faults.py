"""Seeded I/O fault plans — the storage half of the chaos soak.

The resilience claims of the storage shim (:mod:`tpumetrics.resilience.
storage`) are only worth what exercises them: retry/backoff needs flaky
writes, the quarantine path needs corrupt bytes, the durability-degradation
latch needs a disk that is actually full for a while.  This module builds
**deterministic, seeded** fault schedules that install as the shim's
process-global fault injector — the same fault plan (seed) always fires the
same faults at the same shim call indices, so a red soak epoch replays
exactly and the pinned schedules in ``tests/test_soak.py`` stay stable.

A :class:`FaultPlan` is JSON-round-trippable so the soak supervisor can
ship it to worker subprocesses over ``--fault-plan`` (the workers own the
evaluator whose cut writes the faults must hit; injecting in the
supervisor process would miss every seam that matters).

Fault kinds (``IOFault.kind``):

``eio``
    Raise transient ``EIO`` on matching calls — the shim must absorb these
    via retry/backoff (``io_retry`` ledger events, zero data loss).
``enospc``
    Raise permanent ``ENOSPC`` for a bounded window — the evaluator must
    latch durability degradation, keep serving from HBM, and resume (with
    an immediate cut) once the window passes.
``slow_io``
    Sleep ``delay_s`` on matching calls — exercises retry deadlines and
    the heal probe's backoff without failing anything.
``torn_write``
    Truncate the temp file to half its bytes just before the atomic
    rename — the classic torn write.  CRC verification must catch it and
    the reader must fall back + quarantine.
``bit_flip``
    Flip one byte of the FINAL file right after the rename — silent media
    corruption.  Same detection contract as ``torn_write``.
``vanish``
    Unlink the final file right after the rename — a lying close/rename
    (the metadata landed, the data did not).  Readers must treat the
    missing member like any other incomplete cut.

Injection points are the shim's documented ops: ``open``/``write``/
``fsync``/``replace``/``post_replace`` (tmp-file path for the first
three, final path for the last two) and ``read``.
"""

from __future__ import annotations

import dataclasses
import errno as _errno
import json
import os
import random
import time
from typing import Any, Dict, List, Optional, Tuple

from tpumetrics.resilience import storage as _storage

__all__ = [
    "FAULT_KINDS",
    "FaultPlan",
    "IOFault",
    "plan_for_incident",
    "torn_truncate",
]

FAULT_KINDS = ("eio", "enospc", "slow_io", "torn_write", "bit_flip", "vanish")

#: kinds that RAISE into the shim (the others corrupt/delay out-of-band)
_RAISING = {"eio": _errno.EIO, "enospc": _errno.ENOSPC}


@dataclasses.dataclass(frozen=True)
class IOFault:
    """One scheduled fault: fire ``count`` times on shim op ``op`` starting
    at that op's ``after``-th call (per-op call indices are 0-based and
    counted by the plan — deterministic given a deterministic workload).
    ``path_contains`` narrows matching to paths carrying the substring
    (e.g. a rank directory); ``delay_s`` only applies to ``slow_io``."""

    kind: str
    op: str
    after: int = 0
    count: int = 1
    path_contains: str = ""
    delay_s: float = 0.0

    def __post_init__(self) -> None:
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (one of {FAULT_KINDS})")
        if self.count < 1:
            raise ValueError(f"count must be >= 1, got {self.count}")
        if self.after < 0:
            raise ValueError(f"after must be >= 0, got {self.after}")

    def matches(self, op: str, path: str, index: int) -> bool:
        return (
            op == self.op
            and self.after <= index < self.after + self.count
            and (not self.path_contains or self.path_contains in path)
        )


def torn_truncate(path: str) -> None:
    """Truncate ``path`` to half its size — the canonical torn write (never
    raises: a fault that cannot land must not break the write it was meant
    to tear).  Public because the soak supervisor also tears cut members
    directly on disk for ``corrupt_cut`` incidents."""
    try:
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(max(size // 2, 1))
    except OSError:
        pass


def _corrupt_flip(path: str) -> None:
    """Flip one byte in the middle of ``path`` (deterministic offset)."""
    try:
        size = os.path.getsize(path)
        if size == 0:
            return
        with open(path, "r+b") as fh:
            fh.seek(size // 2)
            byte = fh.read(1)
            fh.seek(size // 2)
            fh.write(bytes([byte[0] ^ 0xFF]) if byte else b"\xff")
    except OSError:
        pass


def _vanish(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


class FaultPlan:
    """A deterministic schedule of :class:`IOFault`\\ s, installable as the
    storage shim's fault injector (callable with the ``(op, path)``
    protocol).  Per-op call counting makes firing a pure function of the
    shim call sequence; ``fired`` records every hit for assertions."""

    def __init__(self, faults: List[IOFault]) -> None:
        self.faults = list(faults)
        self._calls: Dict[str, int] = {}
        self.fired: List[Tuple[str, str, int]] = []  # (kind, op, index)

    # ------------------------------------------------------------- injector

    def __call__(self, op: str, path: str) -> None:
        index = self._calls.get(op, 0)
        self._calls[op] = index + 1
        for fault in self.faults:
            if not fault.matches(op, path, index):
                continue
            self.fired.append((fault.kind, op, index))
            if fault.kind in _RAISING:
                num = _RAISING[fault.kind]
                raise OSError(num, os.strerror(num))
            if fault.kind == "slow_io":
                time.sleep(fault.delay_s)
            elif fault.kind == "torn_write":
                torn_truncate(path)
            elif fault.kind == "bit_flip":
                _corrupt_flip(path)
            elif fault.kind == "vanish":
                _vanish(path)

    def install(self) -> None:
        _storage.set_fault_injector(self)

    @staticmethod
    def uninstall() -> None:
        _storage.clear_fault_injector()

    # ----------------------------------------------------------- round-trip

    def to_json(self) -> str:
        return json.dumps(
            [dataclasses.asdict(f) for f in self.faults], sort_keys=True
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls([IOFault(**spec) for spec in json.loads(text)])

    # ------------------------------------------------------------- seeding

    @classmethod
    def from_seed(
        cls, seed: int, profile: str, *, path_contains: str = ""
    ) -> "FaultPlan":
        """Compile a seeded plan for one storage-incident profile.

        ``io_flaky``   — a burst of transient ``eio`` across write/fsync
        plus one ``slow_io`` stall: everything must succeed via retries.
        ``disk_full``  — a bounded ``enospc`` window on the write path:
        durability degrades, serving continues, the window heals.
        ``corrupt_cut`` — one seeded corruption (``torn_write`` /
        ``bit_flip`` / ``vanish``) of a written file: CRC fallback +
        quarantine.

        Deterministic: the same ``(seed, profile)`` always compiles the
        same plan (``random.Random(seed)``, no ambient entropy).
        """
        rng = random.Random(f"{int(seed)}:{profile}")  # str-seeded: stable across runs
        kw = {"path_contains": path_contains}
        if profile == "io_flaky":
            faults = [
                IOFault("eio", "write", after=rng.randrange(0, 3),
                        count=rng.randrange(1, 3), **kw),
                IOFault("eio", "fsync", after=rng.randrange(0, 3),
                        count=rng.randrange(1, 3), **kw),
                IOFault("slow_io", "replace", after=rng.randrange(0, 4),
                        delay_s=0.02, **kw),
            ]
        elif profile == "disk_full":
            faults = [
                IOFault("enospc", "write", after=rng.randrange(0, 2),
                        count=rng.randrange(2, 5), **kw),
            ]
        elif profile == "corrupt_cut":
            kind = rng.choice(("torn_write", "bit_flip", "vanish"))
            op = "replace" if kind == "torn_write" else "post_replace"
            faults = [IOFault(kind, op, after=rng.randrange(0, 2), **kw)]
        else:
            raise ValueError(
                f"unknown fault profile {profile!r} "
                "(one of io_flaky/disk_full/corrupt_cut)"
            )
        return cls(faults)


def plan_for_incident(
    kind: str, seed: int, *, path_contains: str = ""
) -> Optional[FaultPlan]:
    """The storage-incident-kind → fault-plan mapping the soak supervisor
    ships to workers (``None`` for non-storage incident kinds)."""
    if kind in ("io_flaky", "disk_full", "corrupt_cut"):
        return FaultPlan.from_seed(seed, kind, path_contains=path_contains)
    return None
