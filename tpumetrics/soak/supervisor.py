"""The chaos-soak supervisor: spawn, injure, recover, verify — repeatedly.

Owns a pool of real worker subprocesses (:mod:`tpumetrics.soak.worker`),
executes a deterministic :class:`~tpumetrics.soak.schedule.ChaosSchedule`,
and asserts the standing recovery gates after EVERY incident:

1. **Bit-identity.**  The newest restorable cut, folded in-process, must
   ``compute()`` bit-identically to the uninterrupted single-world oracle
   over exactly the committed stream prefix (for a scheduled quorum-degraded
   restore, the oracle excludes precisely the victim's leg batches — the
   expected degraded value is still exact, never "approximately right").
2. **Exactly-once.**  Every restoring rank must adopt exactly the committed
   position: an abrupt kill rolls back to the last cut and the tail is
   re-fed once; a graceful drain covers every fed batch with zero loss.
3. **Bounded restore latency.**  Each recovery cycle's wall time (max over
   ranks) must stay under the schedule's declared ceiling; the per-cycle
   series feeds the ``chaos_soak`` bench gates (p50/p99).
4. **Telemetry continuity.**  One ``elastic_restore`` ledger event per
   restoring rank per cycle, ``elastic_degraded`` exactly when scheduled,
   and one flight-recorder dump per induced incident (the dying side's own
   ``preemption_drain`` dump for graceful incidents, the supervisor's
   incident dump always).

A failed gate marks the incident unrecovered, aborts the remaining schedule
(the state is no longer trustworthy), and surfaces in the report — the
pytest/bench gates assert ``unrecovered == 0``.
"""

from __future__ import annotations

import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from tpumetrics.soak.schedule import (
    STORAGE_KINDS as _STORAGE_KINDS,
    ChaosSchedule,
    Incident,
)
from tpumetrics.soak.traffic import make_metric, oracle_value, values_equal
from tpumetrics.utils.exceptions import TPUMetricsUserError

__all__ = ["ChaosSoakError", "SoakSupervisor", "run_soak"]

_READY_TIMEOUT = 300.0  # first jax import + backend init per worker
_CMD_TIMEOUT = 300.0  # any single command (first feed pays the XLA compile)


class ChaosSoakError(TPUMetricsUserError):
    """A soak invariant failed (a gate, a wedged worker, a bad schedule)."""


class _WorkerHandle:
    """One rank subprocess + a reader thread draining its stdout lines."""

    def __init__(self, proc: subprocess.Popen, rank: int) -> None:
        self.proc = proc
        self.rank = rank
        self._lines: "queue.Queue[Optional[str]]" = queue.Queue()
        self._reader = threading.Thread(target=self._read, daemon=True)
        self._reader.start()

    def _read(self) -> None:
        for line in self.proc.stdout:  # type: ignore[union-attr]
            self._lines.put(line)
        self._lines.put(None)  # EOF

    def send(self, obj: Dict[str, Any]) -> None:
        try:
            self.proc.stdin.write(json.dumps(obj) + "\n")  # type: ignore[union-attr]
            self.proc.stdin.flush()  # type: ignore[union-attr]
        except (BrokenPipeError, OSError) as err:
            raise ChaosSoakError(
                f"rank {self.rank}: worker pipe closed while sending {obj.get('cmd')!r} "
                f"({err}) — the process died unexpectedly (rc={self.proc.poll()})."
            ) from err

    def recv(self, timeout: float = _CMD_TIMEOUT) -> Dict[str, Any]:
        try:
            line = self._lines.get(timeout=timeout)
        except queue.Empty:
            raise ChaosSoakError(
                f"rank {self.rank}: no response within {timeout}s "
                f"(alive={self.proc.poll() is None})."
            ) from None
        if line is None:
            raise ChaosSoakError(
                f"rank {self.rank}: worker exited (rc={self.proc.poll()}) while a "
                "response was expected."
            )
        try:
            return json.loads(line)
        except json.JSONDecodeError as err:
            raise ChaosSoakError(
                f"rank {self.rank}: undecodable worker line {line!r} ({err})."
            ) from err

    def recv_until(self, key: str, value: Any, timeout: float = _CMD_TIMEOUT) -> Dict[str, Any]:
        """Skip lines until one carries ``key == value`` (tolerates stray
        output such as jax warnings routed through stdout)."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = max(0.1, deadline - time.monotonic())
            msg = self.recv(timeout=remaining)
            if msg.get(key) == value:
                return msg

    def kill(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass
        self.proc.wait()

    def close_pipes(self) -> None:
        for fh in (self.proc.stdin, self.proc.stdout):
            try:
                if fh is not None:
                    fh.close()
            except OSError:
                pass


class SoakSupervisor:
    """Executes one :class:`ChaosSchedule` over a real process pool."""

    def __init__(
        self,
        schedule: ChaosSchedule,
        root: str,
        *,
        python: Optional[str] = None,
        verbose: bool = False,
        admin_port: Optional[int] = None,
    ) -> None:
        self.schedule = schedule
        self.root = os.path.abspath(root)
        self.python = python or sys.executable
        self.verbose = bool(verbose)
        self.admin_port = admin_port
        os.makedirs(self.root, exist_ok=True)
        self._workers: List[_WorkerHandle] = []
        self._epoch = 0
        # stream bookkeeping (module docstring of soak.supervisor):
        self._stream_pos = 0  # next stream index to feed
        self._state_pos = 0  # batches the canonical state covers
        self._epoch_stream_start = 0  # this epoch's feed/assignment base
        self._epoch_state_base = 0  # state position adopted at epoch start
        self._lost: set = set()  # stream indices permanently lost (degraded)
        self._degraded_sticky = False  # degraded round-trips via snapshot meta
        self._cut_stream_pos = 0  # stream position of the newest COMPLETE cut
        self._cut_state_pos = 0  # state position of the newest COMPLETE cut
        # (stream, state) positions of every complete cut, oldest first —
        # the corrupt_cut incident rolls back to the SECOND-newest entry
        # (the newest one just lost a member to corruption)
        self._cut_history: List[tuple] = []
        self._restore_walls: List[float] = []
        self._throughputs: List[float] = []
        # straggler analysis: per-file (size, parsed records) cache so each
        # incident's timeline merge re-parses only files that GREW since the
        # last incident, not the whole soak history (O(new), not O(history))
        self._timeline_cache: Dict[str, Any] = {}
        # federation: the newest telemetry snapshot per rank (refreshed at
        # leg/recovery boundaries — a live scrape serves the cached merge, so
        # the HTTP thread never drives the stdio command protocol; the cache
        # dict itself is the one piece of shared state, hence the lock)
        self._fed_snapshots: Dict[int, Dict[str, Any]] = {}
        self._fed_lock = threading.Lock()
        self._admin: Optional[Any] = None
        # the supervisor's own SLO plane: standing objectives over the soak
        # itself, ticked once per incident; record["slo"] mirrors PR 13's
        # straggler field — an observability annotation, never a gate
        self._unrecovered = 0
        self._slo = self._make_slo_engine()

    # ----------------------------------------------------------- federation

    def _make_slo_engine(self) -> Any:
        from tpumetrics.telemetry.slo import SloEngine, callable_rule

        sched = self.schedule
        rules = [
            callable_rule(
                "soak_restore_latency",
                lambda: (self._restore_walls[-1] * 1e3) if self._restore_walls else None,
                float(sched.restore_ceiling_s) * 1e3,
                budget=1e-3, fast_window_s=3600.0, fast_burn=1.0,
                slow_window_s=7200.0, slow_burn=1.0,
                description="per-cycle restore wall under the schedule ceiling",
            ),
            callable_rule(
                "soak_unrecovered",
                lambda: float(self._unrecovered), 0.0,
                budget=1e-3, fast_window_s=3600.0, fast_burn=1.0,
                slow_window_s=7200.0, slow_burn=1.0,
                description="zero unrecovered incidents",
            ),
        ]
        # unarmed: the supervisor ticks it at incident boundaries (sparse,
        # deterministic) instead of running a sampler thread under chaos
        return SloEngine(rules, sample_every_s=60.0)

    def _slo_summary(self) -> Optional[Dict[str, Any]]:
        """Tick the supervisor SLO plane and summarize it for the incident
        line (breach count + worst burn rate).  Never fatal — the soak must
        not fail on its own alerting."""
        try:
            self._slo.tick()
            status = self._slo.status()
            worst = 0.0
            for entry in status["rules"].values():
                worst = max(worst, entry["burn_fast"], entry["burn_slow"])
            return {
                "breaches": status["violations_total"],
                "breached": status["breached"],
                "worst_burn_rate": round(worst, 4),
            }
        except Exception as err:  # noqa: BLE001 — annotation, not a gate
            return {"error": f"{type(err).__name__}: {err}"}

    def _refresh_federation(self) -> None:
        """Pull every live rank's telemetry snapshot over the command wire
        (never fatal; a mid-teardown refresh just keeps the last view)."""
        if not self._workers:
            return
        try:
            acks = self._cmd_all({"cmd": "telemetry"})
            for w, ack in zip(self._workers, acks):
                snap = ack.get("snapshot")
                if snap:
                    with self._fed_lock:
                        self._fed_snapshots[w.rank] = snap
        except Exception:  # noqa: BLE001 — observability, not a soak gate
            pass

    def federation_snapshots(self) -> List[Dict[str, Any]]:
        """The cached per-rank snapshots, rank order (the admin server's
        federation provider — called from the HTTP thread, so the read
        takes the cache lock a leg-boundary refresh writes under)."""
        with self._fed_lock:
            return [self._fed_snapshots[r] for r in sorted(self._fed_snapshots)]

    def federation_summary(self) -> Optional[Dict[str, Any]]:
        """Merged pool view for the soak report (never fatal)."""
        try:
            snaps = self.federation_snapshots()
            if not snaps:
                return None
            from tpumetrics.telemetry import federate as _federate

            view = _federate.merge_snapshots(snaps)
            status = view.statusz()
            return {
                "world": status["world"],
                "ranks": status["ranks"],
                "submit_p99_ms": status["latency"]["submit_ms"]["p99"],
                "restore_p99_ms": status["latency"]["restore_ms"]["p99"],
                "ledger_events": status["ledger"].get("counts_by_kind", {}),
            }
        except Exception as err:  # noqa: BLE001
            return {"error": f"{type(err).__name__}: {err}"}

    def start_admin(self, port: int = 0) -> Any:
        """Start the pool-wide federated admin endpoint: ``/metrics`` and
        ``/statusz`` serve the MERGED view of every rank's cached snapshot
        — live what ``timeline.merge_timelines`` only does post-hoc."""
        from tpumetrics.telemetry.serve import start_admin_server

        if self._admin is None:
            self._admin = start_admin_server(
                port,
                federation=self.federation_snapshots,
                name="soak-supervisor",
            )
        return self._admin

    # ----------------------------------------------------------------- pool

    def _env(self) -> Dict[str, str]:
        import tpumetrics

        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("AXON_POOL_SVC_OVERRIDE", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = ""  # one CPU device per worker
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(tpumetrics.__file__)))
        extra = env.get("PYTHONPATH")
        env["PYTHONPATH"] = pkg_parent + (os.pathsep + extra if extra else "")
        # warm XLA programs across epochs: every respawned world replays the
        # same bucketed step signatures, which is exactly what the
        # persistent compile cache amortizes
        env.setdefault(
            "JAX_COMPILATION_CACHE_DIR", os.path.join(self.root, "jax_cache")
        )
        env.setdefault("TPUMETRICS_FLIGHT_DIR", os.path.join(self.root, "flight"))
        return env

    def _spawn(self, world: int) -> None:
        sched = self.schedule
        self._workers = []
        for rank in range(world):
            proc = subprocess.Popen(
                [
                    self.python, "-m", "tpumetrics.soak.worker",
                    "--rank", str(rank), "--world", str(world),
                    "--epoch", str(self._epoch), "--root", self.root,
                    "--traffic-seed", str(sched.traffic_seed),
                    "--num-classes", str(sched.num_classes),
                    "--max-rows", str(sched.max_rows),
                    "--keep-cuts", str(sched.keep_cuts),
                    "--barrier-timeout", str(sched.barrier_timeout_s),
                ],
                env=self._env(),
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL if not self.verbose else None,
                text=True,
            )
            self._workers.append(_WorkerHandle(proc, rank))
        for w in self._workers:
            w.recv_until("event", "ready", timeout=_READY_TIMEOUT)
        self._log(f"epoch {self._epoch}: world {world} ready")

    def _cmd_all(
        self, cmd: Dict[str, Any], timeout: float = _CMD_TIMEOUT
    ) -> List[Dict[str, Any]]:
        for w in self._workers:
            w.send(cmd)
        out = []
        for w in self._workers:
            resp = w.recv_until("cmd", cmd["cmd"], timeout=timeout)
            if not resp.get("ok"):
                raise ChaosSoakError(
                    f"rank {w.rank}: command {cmd['cmd']!r} failed: {resp.get('error')}"
                )
            out.append(resp)
        return out

    def _teardown(self, kill: bool = True) -> None:
        for w in self._workers:
            if kill:
                w.kill()
            w.close_pipes()
        self._workers = []

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(f"[soak] {msg}", file=sys.stderr, flush=True)

    # ------------------------------------------------------------- the legs

    def _feed(self, start: int, stop: int) -> int:
        """Feed stream indices [start, stop) across the pool; returns rows."""
        if stop <= start:
            return 0
        acks = self._cmd_all(
            {"cmd": "feed", "start": start, "stop": stop, "base": self._epoch_stream_start}
        )
        fed = sum(a["batches"] for a in acks)
        if fed != stop - start:
            raise ChaosSoakError(
                f"feed [{start}, {stop}) applied {fed} batches across the pool, "
                f"expected {stop - start}: the strided sharding drifted."
            )
        self._stream_pos = stop
        self._state_pos += stop - start
        return sum(a["rows"] for a in acks)

    def _cut(self) -> bool:
        """One coordinated cut across the pool.  Advances the committed
        positions only when EVERY rank durably wrote its member — under a
        disk_full fault window ranks ack the attempt with ``path: None``
        (durability degraded, still serving), and an incomplete cut must
        not move the exactly-once anchor the next restore is gated on."""
        acks = self._cmd_all({"cmd": "cut"})
        if not all(a.get("path") for a in acks):
            return False
        self._cut_stream_pos = self._stream_pos
        self._cut_state_pos = self._state_pos
        if not self._cut_history or self._cut_history[-1] != (
            self._cut_stream_pos, self._cut_state_pos
        ):
            self._cut_history.append((self._cut_stream_pos, self._cut_state_pos))
        return True

    def _run_leg(self, inc: Incident) -> float:
        """Feed the incident's leg (cuts every ``cut_every``; an abrupt
        incident's ``tail`` is fed after the last cut).  Returns rows/s."""
        covered = inc.feed - inc.tail
        if covered < 1:
            raise ChaosSoakError(f"incident leg covers no batches: {inc}")
        t0 = time.monotonic()
        rows = 0
        pos = self._stream_pos
        end_covered = pos + covered
        while pos < end_covered:
            chunk_end = min(pos + self.schedule.cut_every, end_covered)
            rows += self._feed(pos, chunk_end)
            pos = chunk_end
            self._cut()
        if inc.tail:
            rows += self._feed(pos, pos + inc.tail)
        wall = max(time.monotonic() - t0, 1e-9)
        # leg boundary: the pool is alive and quiescent — refresh the
        # federated view here so a live scrape serves this leg's state
        self._refresh_federation()
        return rows / wall

    # ----------------------------------------------------------- incidents

    def _induce(self, inc: Incident) -> Dict[str, Any]:
        """Execute the failure mechanism; returns mechanism details."""
        from tpumetrics.telemetry.export import note_incident

        note_incident(
            "chaos_incident", incident=inc.kind, epoch=self._epoch,
            stream_pos=self._stream_pos,
        )
        if inc.kind in _STORAGE_KINDS:
            return self._induce_storage(inc)
        if inc.abrupt:
            victim = self._workers[inc.target_rank]
            victim_pid = victim.proc.pid
            os.kill(victim_pid, signal.SIGKILL)
            victim.proc.wait()
            # slice teardown: the surviving ranks go away without a cut,
            # exactly as a reclaimed fleet does
            for w in self._workers:
                if w is victim:
                    continue
                try:
                    w.send({"cmd": "abort"})
                except ChaosSoakError:
                    pass
            self._teardown()
            details: Dict[str, Any] = {"mechanism": "sigkill", "victim": inc.target_rank}
            if inc.lose_member:
                removed = self._destroy_newest_member(inc.target_rank)
                details["destroyed_member"] = removed
            # rollback: everything after the last cut is gone; the tail
            # will be re-fed by the next epoch (exactly-once via restore)
            self._stream_pos = self._cut_stream_pos
            self._state_pos = self._cut_state_pos
            if inc.lose_member:
                # the victim's member of the newest cut is gone too: its leg
                # batches (strided assignment within this epoch) are lost for
                # good, and the quorum-degraded restore must adopt EXACTLY
                # the remainder — the expected value stays exact
                victim_leg = [
                    i for i in range(self._epoch_stream_start, self._cut_stream_pos)
                    if (i - self._epoch_stream_start) % self._world_now == inc.target_rank
                ]
                self._lost.update(victim_leg)
                self._state_pos -= len(victim_leg)
                self._cut_state_pos -= len(victim_leg)
                details["lost_batches"] = len(victim_leg)
            return details
        return self._induce_graceful()

    def _induce_graceful(self) -> Dict[str, Any]:
        """SIGTERM the whole pool, collect typed drained statuses; the final
        coordinated cut covers every batch fed so far (zero loss)."""
        for w in self._workers:
            try:
                os.kill(w.proc.pid, signal.SIGTERM)
            except OSError:
                pass
        drained = []
        for w in self._workers:
            msg = w.recv_until("event", "drained", timeout=_CMD_TIMEOUT)
            drained.append(msg)
            w.proc.wait()
        self._teardown(kill=False)
        for msg in drained:
            if msg.get("flight") is None or not os.path.isfile(str(msg.get("flight"))):
                raise ChaosSoakError(
                    f"rank {msg.get('rank')}: graceful drain left no flight dump."
                )
            report = msg.get("report") or {}
            if report.get("partial"):
                raise ChaosSoakError(
                    f"rank {msg.get('rank')}: graceful drain returned a PARTIAL "
                    f"report ({report.get('reason')}) — the final cut did not "
                    f"cover {report.get('uncovered_batches')} batch(es)."
                )
        # a polite preemption loses nothing: the final coordinated cut
        # covers every batch fed so far
        self._cut_stream_pos = self._stream_pos
        self._cut_state_pos = self._state_pos
        if not self._cut_history or self._cut_history[-1] != (
            self._cut_stream_pos, self._cut_state_pos
        ):
            self._cut_history.append((self._cut_stream_pos, self._cut_state_pos))
        return {
            "mechanism": "sigterm",
            "drain_s_max": max(d.get("drain_s", 0.0) for d in drained),
            "drain_flights": [d.get("flight") for d in drained],
        }

    # ---------------------------------------------------- storage incidents

    def _arm_storage_faults(self, inc: Incident) -> Optional[Dict[str, Any]]:
        """Arm a seeded per-rank fault plan in every worker for this leg
        (``io_flaky``/``disk_full`` only); deterministic in (schedule seed,
        epoch, rank), so a red soak replays its exact fault sequence."""
        if inc.kind not in ("io_flaky", "disk_full"):
            return None
        from tpumetrics.soak.faults import FaultPlan

        plans: Dict[int, str] = {}
        for w in self._workers:
            seed = self.schedule.seed * 10007 + self._epoch * 101 + w.rank
            plans[w.rank] = FaultPlan.from_seed(seed, inc.kind).to_json()
            w.send({"cmd": "faults", "plan": plans[w.rank]})
        for w in self._workers:
            resp = w.recv_until("cmd", "faults")
            if not resp.get("ok") or not resp.get("armed"):
                raise ChaosSoakError(
                    f"rank {w.rank}: fault plan failed to arm: {resp.get('error')}"
                )
        return {"profile": inc.kind, "plans": plans}

    def _induce_storage(self, inc: Incident) -> Dict[str, Any]:
        """The storage-incident mechanisms + their shim-specific gates (the
        generic exactly-once/latency/ledger gates still run in
        :meth:`_recover` afterwards)."""
        details: Dict[str, Any] = {"mechanism": inc.kind}
        if inc.kind in ("io_flaky", "disk_full"):
            # close the fault window BEFORE judging: the gates below reason
            # about what the shim absorbed while the window was open
            self._cmd_all({"cmd": "faults", "plan": None})
            if inc.kind == "io_flaky":
                n_retry = self._ledger_events(self._epoch, "io_retry")
                if n_retry < 1:
                    raise ChaosSoakError(
                        "io_flaky leg recorded no io_retry events: the fault "
                        "window missed every durability write (schedule bug) "
                        "or retries are not instrumented."
                    )
                if self._cut_stream_pos != self._stream_pos:
                    raise ChaosSoakError(
                        f"io_flaky leg left the newest complete cut at "
                        f"{self._cut_stream_pos} < stream {self._stream_pos}: "
                        "transient faults must be fully absorbed by retries."
                    )
                details["io_retry_events"] = n_retry
            else:  # disk_full
                n_deg = self._ledger_events(self._epoch, "durability_degraded")
                if n_deg < 1:
                    raise ChaosSoakError(
                        "disk_full leg latched no durability_degraded window: "
                        "the ENOSPC burst missed every cut write."
                    )
                # the window is closed: one explicit heal cut must succeed
                # and resume durability
                t_heal = time.monotonic()
                if not self._cut():
                    raise ChaosSoakError(
                        "heal cut still failed after the ENOSPC window closed."
                    )
                details["heal_cut_s"] = time.monotonic() - t_heal
                n_res = self._ledger_events(self._epoch, "durability_resumed")
                if n_res < 1:
                    raise ChaosSoakError(
                        "durability did not resume after the heal cut "
                        "(no durability_resumed event)."
                    )
                details["degraded_events"] = n_deg
                details["resumed_events"] = n_res
            details.update(self._induce_graceful())
            details["mechanism"] = inc.kind
            return details
        # corrupt_cut: tear the slice down abruptly, then corrupt the
        # victim's member of the newest cut on disk — the next world must
        # fall back, quarantine, and re-feed exactly-once
        for w in self._workers:
            try:
                w.send({"cmd": "abort"})
            except ChaosSoakError:
                pass
        self._teardown()
        corrupted = self._corrupt_newest_member(inc.target_rank)
        if corrupted is None:
            raise ChaosSoakError(
                f"corrupt_cut: rank {inc.target_rank} has no cut member to corrupt."
            )
        if len(self._cut_history) < 2:
            raise ChaosSoakError(
                "corrupt_cut needs at least two complete cuts on disk "
                "(schedule guarantees >= 3 in-leg cuts — bookkeeping bug?)."
            )
        # roll back to the newest SURVIVING complete cut; the corrupted
        # one can never restore complete again
        self._cut_history.pop()
        prev_stream, prev_state = self._cut_history[-1]
        self._stream_pos = self._cut_stream_pos = prev_stream
        self._state_pos = self._cut_state_pos = prev_state
        details.update({"victim": inc.target_rank, "corrupted_member": corrupted})
        return details

    def _corrupt_newest_member(self, rank: int) -> Optional[str]:
        """Corrupt (torn-truncate) the victim rank's newest cut member in
        place — the media-corruption sibling of
        :meth:`_destroy_newest_member`, which models total loss."""
        from tpumetrics.runtime.snapshot import list_snapshots
        from tpumetrics.soak.faults import torn_truncate

        directory = os.path.join(self.root, "snapshots", f"rank-{rank:05d}")
        snaps = list_snapshots(directory)
        if not snaps:
            return None
        _, path = snaps[-1]
        torn_truncate(path)
        return path

    @property
    def _world_now(self) -> int:
        return self.schedule.worlds[self._epoch]

    def _destroy_newest_member(self, rank: int) -> Optional[str]:
        """The killed-with-its-disk failure mode: remove the victim rank's
        newest snapshot file (its member of the newest cut)."""
        from tpumetrics.runtime.snapshot import list_snapshots

        directory = os.path.join(self.root, "snapshots", f"rank-{rank:05d}")
        snaps = list_snapshots(directory)
        if not snaps:
            return None
        _, path = snaps[-1]
        os.unlink(path)
        return path

    # ---------------------------------------------------------- verification

    def _committed(self) -> List[int]:
        return [i for i in range(self._cut_stream_pos) if i not in self._lost]

    def _verify_fold(self, quorum_min_ranks: Optional[int]) -> Dict[str, Any]:
        """Supervisor-side gate 1: fold the newest restorable cut in-process
        and compare bit-identically to the oracle over the committed
        prefix."""
        from tpumetrics.resilience.elastic import QuorumPolicy, load_latest_cut

        sched = self.schedule
        proto = make_metric(sched.num_classes)
        cut = load_latest_cut(
            os.path.join(self.root, "snapshots"),
            template=proto.init_state(),
            quorum=QuorumPolicy(min_ranks=quorum_min_ranks) if quorum_min_ranks else None,
            mode="bucketed",
        )
        if cut is None:
            raise ChaosSoakError("verification found no elastic cut at all")
        folded = proto.fold_state_dicts([cut.payloads[r] for r in sorted(cut.payloads)])
        got = {
            k: np.asarray(v) for k, v in proto.functional_compute(folded).items()
        }
        want = oracle_value(
            sched.traffic_seed, self._committed(),
            num_classes=sched.num_classes, max_rows=sched.max_rows,
        )
        if not values_equal(got, want):
            raise ChaosSoakError(
                f"recovered compute() diverged from the uninterrupted oracle at "
                f"cut step {cut.step}: got {got}, want {want} "
                f"(committed={len(self._committed())}, lost={len(self._lost)})."
            )
        return {
            "cut_step": cut.step,
            "cut_world": cut.world_size,
            "degraded": cut.degraded,
            "value": {k: v.tolist() for k, v in got.items()},
        }

    def _cached_streams(self) -> Dict[Any, List[Dict[str, Any]]]:
        """The per-rank telemetry streams, parsed incrementally: a file
        whose size is unchanged since the last incident serves its cached
        records (past epochs' files never change; only the current epoch's
        grow), so the per-incident cost is O(new records), not
        O(soak history)."""
        from tpumetrics.telemetry import timeline as _timeline

        directory = os.path.join(self.root, "telemetry")
        streams: Dict[Any, List[Dict[str, Any]]] = {}
        if not os.path.isdir(directory):
            return streams
        for name in sorted(os.listdir(directory)):
            m = _timeline.RANK_FILE_RE.search(name)
            if not m:
                continue
            path = os.path.join(directory, name)
            size = os.path.getsize(path)
            cached = self._timeline_cache.get(path)
            if cached is None or cached[0] != size:
                # parse just this file (load_rank_streams would re-read all),
                # through the timeline's ONE parse rule
                self._timeline_cache[path] = (size, _timeline.parse_jsonl(path))
            key = (int(m.group(2)), int(m.group(1)))  # (rank, epoch)
            records = self._timeline_cache[path][1]
            if records:
                streams.setdefault(key, []).extend(records)
        return streams

    def _straggler_summary(self) -> Optional[Dict[str, Any]]:
        """Merge the per-rank telemetry streams flushed so far into one
        clock-aligned timeline and summarize the cross-rank skew — the
        "which rank is the straggler" answer attached to every incident
        line.  Never fatal: a soak must not fail on its own analysis."""
        from tpumetrics.telemetry import timeline as _timeline

        try:
            merged = _timeline.merge_timelines(self._cached_streams())
            if not merged.events:
                return None
            report = _timeline.straggler_report(merged)
            return {
                "straggler": report["straggler"],
                "n_windows": report["n_windows"],
                "max_skew_ms": round(report["max_skew_ms"], 3),
                "mean_skew_ms": round(report["mean_skew_ms"], 3),
                "slowest_counts": report["slowest_counts"],
            }
        except Exception as err:  # noqa: BLE001 — analysis must not fail the soak
            return {"error": f"{type(err).__name__}: {err}"}

    def _ledger_events(self, epoch: int, kind: str) -> int:
        tel_dir = os.path.join(self.root, "telemetry")
        count = 0
        if not os.path.isdir(tel_dir):
            return 0
        prefix = f"epoch{epoch:03d}-"
        for name in os.listdir(tel_dir):
            if not name.startswith(prefix):
                continue
            with open(os.path.join(tel_dir, name)) as fh:
                for line in fh:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if rec.get("kind") == kind:
                        count += 1
        return count

    def _recover(self, inc: Incident) -> Dict[str, Any]:
        """Spawn the post-incident world, restore every rank, assert the
        exactly-once/latency/telemetry gates."""
        sched = self.schedule
        quorum = 1 if inc.lose_member else None
        self._epoch += 1
        t0 = time.monotonic()
        self._spawn(inc.world_after)
        acks = self._cmd_all({"cmd": "restore", "quorum_min_ranks": quorum})
        restore_wall = time.monotonic() - t0
        infos = [a["restore"] for a in acks]
        if any(info is None for info in infos):
            raise ChaosSoakError("a restoring rank found no cut to adopt")
        positions = {int(info["batches"]) for info in infos}
        if positions != {self._cut_state_pos}:
            raise ChaosSoakError(
                f"exactly-once violated: restoring ranks adopted positions "
                f"{sorted(positions)}, expected {{{self._cut_state_pos}}} — the fold "
                "double-counted or skipped part of the stream."
            )
        # the degraded flag round-trips via snapshot meta BY DESIGN: once a
        # quorum-degraded restore happened, every later restore stays marked
        expect_degraded = bool(inc.lose_member) or self._degraded_sticky
        degraded = {bool(info["degraded"]) for info in infos}
        if degraded != {expect_degraded}:
            raise ChaosSoakError(
                f"degraded flags {degraded} do not match the schedule "
                f"(lose_member={inc.lose_member}, sticky={self._degraded_sticky})."
            )
        if inc.lose_member:
            self._degraded_sticky = True
        max_restore_call_s = max(float(a["wall_s"]) for a in acks)
        if max_restore_call_s > sched.restore_ceiling_s:
            raise ChaosSoakError(
                f"restore latency {max_restore_call_s:.2f}s exceeds the declared "
                f"ceiling {sched.restore_ceiling_s}s."
            )
        # telemetry continuity: one elastic_restore per restoring rank; the
        # degraded event exactly when scheduled
        n_restore = self._ledger_events(self._epoch, "elastic_restore")
        if n_restore != inc.world_after:
            raise ChaosSoakError(
                f"ledger continuity: {n_restore} elastic_restore event(s) for epoch "
                f"{self._epoch}, expected {inc.world_after}."
            )
        n_degraded = self._ledger_events(self._epoch, "elastic_degraded")
        if bool(n_degraded) != bool(inc.lose_member):
            raise ChaosSoakError(
                f"ledger continuity: {n_degraded} elastic_degraded event(s) for epoch "
                f"{self._epoch}, schedule expected degraded={inc.lose_member}."
            )
        storage_gates: Dict[str, Any] = {}
        if inc.kind == "corrupt_cut":
            # the storage-specific continuity gates: the corrupted member
            # must have been QUARANTINED (not silently skipped) and every
            # rank's fallback walk must stay inside the retention window.
            # fallback_depth legitimately differs across concurrently
            # restoring ranks (the first to scan quarantines the member;
            # later ranks never see the incomplete group), so only the max
            # is gated.
            from tpumetrics.resilience.storage import quarantine_census

            depths = [int(info.get("fallback_depth") or 0) for info in infos]
            if max(depths) > sched.keep_cuts:
                raise ChaosSoakError(
                    f"fallback depths {sorted(depths)} exceed the retention "
                    f"window keep_cuts={sched.keep_cuts}: the walk left the "
                    "set of cuts the evaluator promises to keep."
                )
            n_quar = self._ledger_events(self._epoch, "snapshot_quarantined")
            if n_quar < 1:
                raise ChaosSoakError(
                    "no snapshot_quarantined event for the corrupted member: "
                    "the fallback silently skipped corrupt bytes instead of "
                    "quarantining them."
                )
            census = quarantine_census(os.path.join(self.root, "snapshots"))
            if census["files"] < 1:
                raise ChaosSoakError(
                    "quarantine census is empty after a corrupt_cut recovery "
                    "(the ledger said quarantined, the disk disagrees)."
                )
            storage_gates = {
                "fallback_depth_max": max(depths),
                "quarantined_events": n_quar,
                "quarantine_census": census,
            }
        self._restore_walls.append(max_restore_call_s)
        # the new epoch's bases: feed resumes at the cut's stream position
        self._state_pos = self._cut_state_pos
        self._epoch_state_base = self._cut_state_pos
        self._stream_pos = self._cut_stream_pos
        self._epoch_stream_start = self._cut_stream_pos
        self._refresh_federation()  # the new world's first federated view
        return {
            "adopted": self._cut_state_pos,
            "degraded": expect_degraded,
            "restore_wall_s": restore_wall,
            "restore_call_s_max": max_restore_call_s,
            "restore_ms_evaluator_max": max(
                float(info.get("restore_ms", 0.0)) for info in infos
            ),
            "ledger_restore_events": n_restore,
            "ledger_degraded_events": n_degraded,
            **storage_gates,
        }

    # ------------------------------------------------------------------ run

    def run(self) -> Dict[str, Any]:
        """Execute the whole schedule; returns the soak report dict."""
        from tpumetrics.telemetry.export import (
            disable_flight_recorder,
            enable_flight_recorder,
            flight_dump,
            flight_recorder,
        )

        sched = self.schedule
        prior = flight_recorder()
        enable_flight_recorder(os.path.join(self.root, "flight"))
        incidents_out: List[Dict[str, Any]] = []
        unrecovered = 0
        final: Dict[str, Any] = {}
        try:
            if self.admin_port is not None:
                self.start_admin(int(self.admin_port))
            self._spawn(sched.world)
            for idx, inc in enumerate(sched.incidents):
                record: Dict[str, Any] = {
                    "index": idx,
                    "kind": inc.kind,
                    "world_before": sched.worlds[idx],
                    "world_after": inc.world_after,
                    "abrupt": inc.abrupt,
                    "lose_member": inc.lose_member,
                    "feed": inc.feed,
                    "tail": inc.tail,
                }
                try:
                    armed = self._arm_storage_faults(inc)
                    if armed is not None:
                        record["faults"] = armed
                    throughput = self._run_leg(inc)
                    record["throughput_rows_per_s"] = round(throughput, 1)
                    self._throughputs.append(throughput)
                    record["stream_pos"] = self._stream_pos
                    record.update(self._induce(inc))
                    record.update(self._recover(inc))
                    record["verify"] = self._verify_fold(1 if inc.lose_member else None)
                    record["straggler"] = self._straggler_summary()
                    record["slo"] = self._slo_summary()
                    record["flight_dump"] = flight_dump(
                        f"incident-{idx}-{inc.kind}", epoch=self._epoch, index=idx
                    )
                    record["ok"] = True
                    self._log(
                        f"incident {idx} ({inc.kind}) recovered: pos={self._state_pos} "
                        f"world={inc.world_after}"
                    )
                except ChaosSoakError as err:
                    record["ok"] = False
                    record["error"] = str(err)
                    unrecovered += 1
                    self._unrecovered = unrecovered
                    record["slo"] = self._slo_summary()
                    record["flight_dump"] = flight_dump(
                        f"incident-{idx}-{inc.kind}-FAILED", epoch=self._epoch, index=idx
                    )
                    incidents_out.append(record)
                    self._teardown()
                    break
                incidents_out.append(record)
            else:
                # the final pool drains gracefully: one last zero-loss gate
                final_inc = Incident(
                    kind="sigterm", feed=1, world_after=sched.worlds[-1]
                )
                self._feed(self._stream_pos, self._stream_pos + 1)
                final.update(self._induce(final_inc))
                final["verify"] = self._verify_fold(None)
                final["ok"] = True
        except Exception as err:
            unrecovered += 1
            self._unrecovered = unrecovered
            final = {"ok": False, "error": f"{type(err).__name__}: {err}"}
            self._teardown()
        finally:
            self._teardown()
            if self._admin is not None:
                self._admin.close()
                self._admin = None
            try:
                self._slo.close()
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
            if prior is None:
                disable_flight_recorder()
            else:
                enable_flight_recorder(prior.directory, prior.capacity)

        walls = sorted(self._restore_walls)

        def _pct(p: float) -> Optional[float]:
            if not walls:
                return None
            return walls[min(len(walls) - 1, int(round(p * (len(walls) - 1))))]

        return {
            "seed": sched.seed,
            "worlds": list(sched.worlds),
            "incidents": incidents_out,
            "n_incidents": len(sched.incidents),
            "completed": len([r for r in incidents_out if r.get("ok")]),
            "unrecovered": unrecovered,
            "stream_batches": self._stream_pos,
            "lost_batches": len(self._lost),
            "restore_latency_s": {
                "p50": _pct(0.50), "p99": _pct(0.99),
                "max": walls[-1] if walls else None, "count": len(walls),
            },
            "throughput_rows_per_s": {
                "mean": (
                    round(sum(self._throughputs) / len(self._throughputs), 1)
                    if self._throughputs else None
                ),
                "min": round(min(self._throughputs), 1) if self._throughputs else None,
            },
            "federation": self.federation_summary(),
            "final": final,
        }


def run_soak(
    schedule: ChaosSchedule,
    root: str,
    *,
    out_jsonl: Optional[str] = None,
    verbose: bool = False,
    admin_port: Optional[int] = None,
) -> Dict[str, Any]:
    """Execute ``schedule`` under a :class:`SoakSupervisor` rooted at
    ``root``; optionally stream the incident report to ``out_jsonl`` (one
    line per incident, a ``summary`` line last).  ``admin_port`` serves the
    pool-wide federated admin endpoint for the soak's duration.  Returns
    the report."""
    report = SoakSupervisor(schedule, root, verbose=verbose, admin_port=admin_port).run()
    if out_jsonl:
        with open(out_jsonl, "w") as fh:
            for rec in report["incidents"]:
                fh.write(json.dumps({"type": "incident", **rec}, sort_keys=True) + "\n")
            summary = {k: v for k, v in report.items() if k != "incidents"}
            fh.write(json.dumps({"type": "summary", **summary}, sort_keys=True) + "\n")
    return report
