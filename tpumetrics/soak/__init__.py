"""``tpumetrics.soak`` — the chaos-soak harness: a real multi-process pool
under a deterministic preemption/resize schedule, with standing recovery
gates.

The resilience stack (elastic cuts, quorum restore, crash replay, graceful
drain) is exercised elsewhere through in-process emulation
(:class:`~tpumetrics.resilience.faults.FaultInjectionBackend`) — this
package turns the "kill the job anywhere, on any topology" claim into a
*standing gate* over real operating-system processes and real signals:

- :mod:`~tpumetrics.soak.schedule` — a seeded, deterministic chaos schedule
  (:func:`generate_schedule`): SIGKILL at arbitrary points, SIGTERM
  graceful-drain preemptions, and repeated world resizes (grow AND shrink,
  e.g. 4→2→3→4), JSON round-trippable for the CLI.
- :mod:`~tpumetrics.soak.wire` — :class:`FileBarrierBackend`, a host-object
  barrier channel over a shared directory, so the coordinated snapshot cut
  runs across real process boundaries on ANY box (``jax.distributed`` /
  DCN collectives are not required; where they exist the evaluator takes
  the real backend instead — ``tests/multihost``).
- :mod:`~tpumetrics.soak.worker` — one rank = one subprocess driving
  continuous traffic through a :class:`~tpumetrics.runtime.evaluator.
  StreamingEvaluator` (bucketed, donated, elastic snapshots, cut-level
  retention), with a SIGTERM handler that drains gracefully: intake off,
  queue applied, one final coordinated cut, typed exit status.
- :mod:`~tpumetrics.soak.supervisor` — spawns the pool, executes the
  schedule, and after EVERY incident asserts the standing gates:
  ``compute()`` bit-identical to an uninterrupted single-world oracle,
  restore latency under the declared ceiling, exactly-once replay (the
  adopted position equals the covered stream prefix), and telemetry
  continuity (``elastic_restore``/``elastic_degraded`` ledger events match
  the schedule, one flight-recorder dump per induced incident).  Emits a
  JSONL incident report plus a summary with throughput and restore-latency
  p50/p99 — the series the ``chaos_soak`` bench scenario gates.

Three entry points: ``python -m tpumetrics.soak`` (CLI: ``generate`` /
``run`` — schedule file in, incident JSONL out — and ``report``, which
merges a soak's per-rank telemetry into one clock-aligned timeline with a
cross-rank straggler summary via :mod:`tpumetrics.telemetry.timeline`),
the ``-m slow`` pytest short soak (``tests/test_soak.py``), and the
``chaos_soak`` bench scenario (``bench.py``).  See the "Chaos soak &
preemption runbook" section of ``docs/resilience.md``.
"""

from tpumetrics.soak.fleet import FleetSoakError, run_fleet_soak
from tpumetrics.soak.schedule import (
    ChaosSchedule,
    Incident,
    generate_schedule,
)
from tpumetrics.soak.supervisor import ChaosSoakError, SoakSupervisor, run_soak
from tpumetrics.soak.wire import FileBarrierBackend

__all__ = [
    "ChaosSchedule",
    "ChaosSoakError",
    "FileBarrierBackend",
    "FleetSoakError",
    "Incident",
    "SoakSupervisor",
    "generate_schedule",
    "run_fleet_soak",
    "run_soak",
]
