"""Deterministic soak traffic and its uninterrupted single-world oracle.

Batch ``i`` of a soak is a pure function of ``(traffic_seed, i)`` — every
worker of every epoch, the supervisor's oracle, and a post-mortem replay all
derive byte-identical batches from the schedule alone.  The metric under
soak is a :class:`~tpumetrics.collections.MetricCollection` of
integer-sum-state classification metrics (micro accuracy + confusion
matrix): integer folds are associative and order-free, so "bit-identical to
the uninterrupted oracle" is a meaningful gate under ANY world layout, fold
order, or resize history — float-accumulation reordering can never explain
away a discrepancy.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Tuple

import numpy as np

__all__ = ["make_batch", "make_metric", "oracle_value", "values_equal"]


def make_metric(num_classes: int = 5) -> Any:
    """The soak collection: integer sum states only (module docstring)."""
    from tpumetrics import MetricCollection
    from tpumetrics.classification import MulticlassAccuracy, MulticlassConfusionMatrix

    return MetricCollection(
        {
            "acc": MulticlassAccuracy(
                num_classes=num_classes, average="micro", validate_args=False
            ),
            "confmat": MulticlassConfusionMatrix(
                num_classes=num_classes, validate_args=False
            ),
        }
    )


def make_batch(
    traffic_seed: int, index: int, *, num_classes: int = 5, max_rows: int = 8
) -> Tuple[np.ndarray, np.ndarray]:
    """Batch ``index`` as host arrays: ``(preds (n, C) f32, target (n,) i32)``
    with ``n`` seeded in ``[1, max_rows]``."""
    rng = np.random.default_rng([int(traffic_seed), int(index)])
    n = 1 + int(rng.integers(0, int(max_rows)))
    preds = rng.standard_normal((n, int(num_classes))).astype(np.float32)
    target = rng.integers(0, int(num_classes), n).astype(np.int32)
    return preds, target


def oracle_value(
    traffic_seed: int,
    indices: Iterable[int],
    *,
    num_classes: int = 5,
    max_rows: int = 8,
) -> Dict[str, np.ndarray]:
    """The uninterrupted single-world reference over exactly ``indices``:
    one fresh collection, eagerly updated in order, computed on host."""
    import jax
    import jax.numpy as jnp

    metric = make_metric(num_classes)
    for i in indices:
        preds, target = make_batch(
            traffic_seed, i, num_classes=num_classes, max_rows=max_rows
        )
        metric.update(jnp.asarray(preds), jnp.asarray(target))
    return {k: np.asarray(jax.device_get(v)) for k, v in metric.compute().items()}


def values_equal(a: Dict[str, np.ndarray], b: Dict[str, np.ndarray]) -> bool:
    """Bit-identical comparison of two compute() results."""
    if set(a) != set(b):
        return False
    return all(np.array_equal(np.asarray(a[k]), np.asarray(b[k])) for k in a)
