"""Regenerate docs/metrics_index.md and the per-metric pages under
docs/metrics/ from the live package (`python docs/_gen_index.py`).

Every exported Metric class gets a section with its constructor signature,
its full docstring (args, shapes, examples), and the matching
``tpumetrics.functional`` counterpart with its signature and docstring.
"""

import importlib
import inspect
import os
import pkgutil
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import tpumetrics
import tpumetrics.functional as F
from tpumetrics.metric import Metric

# discover every subpackage that exports Metric subclasses, so new domains
# can never silently vanish from the index
DOMS = []
for info in pkgutil.iter_modules(tpumetrics.__path__):
    # plain modules count too (aggregation.py is a module, not a package)
    if info.name.startswith("_") or info.name in ("functional", "utils", "parallel",
                                                  "metric", "collections", "buffers"):
        continue
    mod = importlib.import_module(f"tpumetrics.{info.name}")
    if any(inspect.isclass(o) and issubclass(o, Metric) and o is not Metric
           for o in vars(mod).values()):
        DOMS.append(info.name)
DOMS.sort()


def _snake(name: str) -> str:
    s = re.sub(r"(?<!^)(?=[A-Z][a-z])", "_", name)
    s = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", s)
    return s.lower()


# hand map for classes whose functional name is not the mechanical snake_case
# (None = streaming/protocol metric with no functional form)
_FUNCTIONAL_ALIASES = {
    "MeanAveragePrecision": None,  # COCO protocol over accumulated images
    "MetricTracker": None,
    "FrechetInceptionDistance": None,  # streaming moment states
    "KernelInceptionDistance": None,
    "InceptionScore": None,
    "MemorizationInformedFrechetInceptionDistance": None,
    "PerceptualPathLength": "perceptual_path_length" if hasattr(F, "perceptual_path_length") else None,
    "RetrievalMetric": None,  # abstract base
    "PrecisionAtFixedRecall": None,  # task-dispatch shells
    "RecallAtFixedPrecision": None,
    "SpecificityAtSensitivity": None,
    "ROUGEScore": "rouge_score",
    "BERTScore": "bert_score",
    "InfoLM": "infolm",
    "CLIPScore": "clip_score",
    "CLIPImageQualityAssessment": "clip_image_quality_assessment",
    "SacreBLEUScore": "sacre_bleu_score",
    "BLEUScore": "bleu_score",
    "CHRFScore": "chrf_score",
    "WordErrorRate": "word_error_rate",
    "CharErrorRate": "char_error_rate",
    "SQuAD": "squad",
    "BinaryGroupStatRates": "binary_groups_stat_rates",
    "RetrievalMAP": "retrieval_average_precision",
    "RetrievalMRR": "retrieval_reciprocal_rank",
    "WordInfoLost": "word_information_lost",
    "WordInfoPreserved": "word_information_preserved",
    "MultiScaleStructuralSimilarityIndexMeasure": "multiscale_structural_similarity_index_measure",
}


def _functional_for(cls_name: str):
    if cls_name in _FUNCTIONAL_ALIASES:
        alias = _FUNCTIONAL_ALIASES[cls_name]
        return (getattr(F, alias, None) if isinstance(alias, str) else None)
    for cand in (
        _snake(cls_name),
        _snake(cls_name).replace("_co_ef", "_coef"),
        _snake(cls_name).replace("_corr_coef", "_corrcoef"),
        _snake(cls_name).replace("f_beta", "fbeta"),
        _snake(cls_name).replace("f_beta", "fbeta").replace("_corr_coef", "_corrcoef"),
        _snake(cls_name.replace("IoU", "Iou")),
    ):
        fn = getattr(F, cand, None)
        if callable(fn):
            return fn
    return None


def _clean_doc(obj) -> str:
    doc = inspect.getdoc(obj) or "(no docstring)"
    # demote any headers and fence doctest examples for markdown rendering
    out = []
    in_example = False
    for line in doc.splitlines():
        stripped = line.strip()
        if stripped.startswith("Example") and stripped.rstrip(":") in ("Example", "Examples"):
            out.append("**Example**")
            out.append("```python")
            in_example = True
            continue
        if in_example and stripped and not line.startswith((" ", "\t", ">")) and not stripped.startswith((">>>", "...")):
            out.append("```")
            in_example = False
        out.append(line)
    if in_example:
        out.append("```")
    return "\n".join(out)


def _sig(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def _check_examples() -> None:
    """Generation FAILS if an exported metric class ships without a runnable
    example block: the per-metric pages embed each class docstring, and the
    doctest sweep (tests/test_doctests.py) executes what's embedded — so this
    gate keeps every page's example real, not decorative."""
    missing = [
        n
        for n in tpumetrics.__all__
        if inspect.isclass(getattr(tpumetrics, n, None))
        and issubclass(getattr(tpumetrics, n), Metric)
        and getattr(tpumetrics, n) is not Metric
        and ">>>" not in (inspect.getdoc(getattr(tpumetrics, n)) or "")
    ]
    if missing:
        raise SystemExit(
            f"exported metric classes without a runnable docstring example: {sorted(missing)}"
        )


_check_examples()

os.makedirs(os.path.join(os.path.dirname(__file__), "metrics"), exist_ok=True)

index_lines = ["# All metrics", "", "Generated from the live package (`python docs/_gen_index.py`).", ""]
_RUNTIME_NOTE = (
    "Every metric listed here (and any `MetricCollection` of them) can be wrapped by "
    "[`StreamingEvaluator`](runtime.md) for async ingestion, shape-bucketed batching, and "
    "preemption-safe snapshots; metrics whose states are all `sum`/`max`/`min` tensors take "
    "the jitted bucketed path, the rest run the eager path (`buckets=None`)."
)
total = 0
for d in DOMS:
    mod = importlib.import_module(f"tpumetrics.{d}")
    names = sorted(n for n, o in vars(mod).items()
                   if inspect.isclass(o) and issubclass(o, Metric) and o is not Metric
                   and not n.startswith("_"))
    total += len(names)
    index_lines.append(f"## `tpumetrics.{d}` ({len(names)})\n")
    index_lines.extend(f"- [`{n}`](metrics/{d}.md#{n.lower()})" for n in names)
    index_lines.append("")

    page = [
        f"# {d} metrics",
        "",
        f"Generated from the live package (`python docs/_gen_index.py`). "
        f"Import from `tpumetrics.{d}`.",
        "",
    ]
    for n in names:
        cls = getattr(mod, n)
        page.append(f"## {n}")
        page.append("")
        page.append(f"```python\ntpumetrics.{d}.{n}{_sig(cls.__init__).replace('(self, ', '(').replace('(self)', '()')}\n```")
        page.append("")
        flags = []
        for attr in ("is_differentiable", "higher_is_better", "full_state_update"):
            val = getattr(cls, attr, None)
            if val is not None:
                flags.append(f"`{attr}={val}`")
        if flags:
            page.append("Flags: " + ", ".join(flags))
            page.append("")
        page.append(_clean_doc(cls))
        page.append("")
        fn = _functional_for(n)
        if fn is not None:
            page.append(f"**Functional:** `tpumetrics.functional.{fn.__name__}{_sig(fn)}`")
            page.append("")
            fn_doc = _clean_doc(fn)
            first = fn_doc.split("\n\n")[0]
            if first != "(no docstring)":
                page.append(first)
                page.append("")
    out_page = os.path.join(os.path.dirname(__file__), "metrics", f"{d}.md")
    open(out_page, "w", encoding="utf-8").write("\n".join(page) + "\n")
    print("wrote", out_page)

index_lines.insert(3, f"**{total} metric classes**, each with a `tpumetrics.functional.*`"
                      " counterpart where the reference has one. Click through for"
                      " per-metric args, shapes, and examples.\n")
index_lines.insert(4, _RUNTIME_NOTE + "\n")
out = os.path.join(os.path.dirname(__file__), "metrics_index.md")
open(out, "w", encoding="utf-8").write("\n".join(index_lines) + "\n")
print("wrote", out)
