"""Regenerate docs/metrics_index.md from the live package."""
import importlib
import inspect
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from tpumetrics.metric import Metric

DOMS = ["aggregation", "classification", "regression", "clustering", "nominal", "retrieval",
        "image", "text", "audio", "detection", "multimodal", "wrappers"]

lines = ["# All metrics", "", "Generated from the live package (`python docs/_gen_index.py`).", ""]
total = 0
for d in DOMS:
    mod = importlib.import_module(f"tpumetrics.{d}")
    names = sorted(n for n, o in vars(mod).items()
                   if inspect.isclass(o) and issubclass(o, Metric) and o is not Metric
                   and not n.startswith("_"))
    total += len(names)
    lines.append(f"## `tpumetrics.{d}` ({len(names)})\n")
    lines.extend(f"- `{n}`" for n in names)
    lines.append("")
lines.insert(3, f"**{total} metric classes**, each with a `tpumetrics.functional.*`"
                " counterpart where the reference has one.\n")
out = os.path.join(os.path.dirname(__file__), "metrics_index.md")
open(out, "w").write("\n".join(lines) + "\n")
print("wrote", out)
