"""Regenerate docs/metrics_index.md from the live package."""
import importlib
import inspect
import os
import pkgutil
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import tpumetrics
from tpumetrics.metric import Metric

# discover every subpackage that exports Metric subclasses, so new domains
# can never silently vanish from the index
DOMS = []
for info in pkgutil.iter_modules(tpumetrics.__path__):
    # plain modules count too (aggregation.py is a module, not a package)
    if info.name.startswith("_") or info.name in ("functional", "utils", "parallel",
                                                  "metric", "collections", "buffers"):
        continue
    mod = importlib.import_module(f"tpumetrics.{info.name}")
    if any(inspect.isclass(o) and issubclass(o, Metric) and o is not Metric
           for o in vars(mod).values()):
        DOMS.append(info.name)
DOMS.sort()

lines = ["# All metrics", "", "Generated from the live package (`python docs/_gen_index.py`).", ""]
total = 0
for d in DOMS:
    mod = importlib.import_module(f"tpumetrics.{d}")
    names = sorted(n for n, o in vars(mod).items()
                   if inspect.isclass(o) and issubclass(o, Metric) and o is not Metric
                   and not n.startswith("_"))
    total += len(names)
    lines.append(f"## `tpumetrics.{d}` ({len(names)})\n")
    lines.extend(f"- `{n}`" for n in names)
    lines.append("")
lines.insert(3, f"**{total} metric classes**, each with a `tpumetrics.functional.*`"
                " counterpart where the reference has one.\n")
out = os.path.join(os.path.dirname(__file__), "metrics_index.md")
open(out, "w").write("\n".join(lines) + "\n")
print("wrote", out)
