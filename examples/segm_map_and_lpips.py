"""Instance-mask mAP and LPIPS with the bundled trained heads.

Two round-3 capabilities in one walkthrough:

1. ``MeanAveragePrecision(iou_type="segm")`` — per-image boolean mask stacks
   are RLE-encoded at ``update`` and matched by mask IoU at ``compute``
   (reference ``detection/mean_ap.py:430-438`` semantics, validated
   head-to-head in ``tests/reference_parity/test_map_parity.py``).
2. ``LearnedPerceptualImagePatchSimilarity(net_type="alex",
   backbone_params=...)`` — the trained LPIPS linear heads ship with the
   package; only the backbone convs are supplied (converted offline from
   torchvision, see docs/pretrained_backbones.md — random weights stand in
   here so the example runs hermetically).

Run:
    python examples/segm_map_and_lpips.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np

from tpumetrics.detection import MeanAveragePrecision
from tpumetrics.image import LearnedPerceptualImagePatchSimilarity


def box_masks(boxes, h=64, w=64):
    """Rasterize xyxy boxes into an (N, h, w) boolean mask stack."""
    out = np.zeros((len(boxes), h, w), dtype=bool)
    ys, xs = np.arange(h)[:, None], np.arange(w)[None, :]
    for i, (x1, y1, x2, y2) in enumerate(boxes):
        out[i] = (ys >= y1) & (ys < y2) & (xs >= x1) & (xs < x2)
    return out


def main():
    # ---- 1. segm mAP: predictions slightly shifted against the ground truth
    gt_boxes = np.asarray([[4.0, 4, 24, 24], [30.0, 8, 52, 30], [10.0, 38, 30, 58]])
    pred_boxes = gt_boxes + np.asarray([[1.5, 1.5, 1.5, 1.5], [0, 0, 0, 0], [4, 4, 4, 4]])

    metric = MeanAveragePrecision(iou_type="segm", class_metrics=True)
    metric.update(
        [
            {
                "masks": jnp.asarray(box_masks(pred_boxes)),
                "scores": jnp.asarray([0.9, 0.8, 0.6]),
                "labels": jnp.asarray([0, 1, 0]),
            }
        ],
        [{"masks": jnp.asarray(box_masks(gt_boxes)), "labels": jnp.asarray([0, 1, 0])}],
    )
    result = metric.compute()
    print("segm mAP:", round(float(result["map"]), 4))
    print("segm mAP@50:", round(float(result["map_50"]), 4))
    print("per class:", np.round(np.asarray(result["map_per_class"]), 4))

    # ---- 2. LPIPS: alexnet-shaped backbone + the bundled trained heads
    rng = np.random.default_rng(0)
    plan = [(64, 3, 11), (192, 64, 5), (384, 192, 3), (256, 384, 3), (256, 256, 3)]
    backbone_params = [
        (rng.normal(0, 0.05, (o, i, k, k)).astype(np.float32), np.zeros(o, np.float32))
        for (o, i, k) in plan
    ]

    lpips = LearnedPerceptualImagePatchSimilarity(net_type="alex", backbone_params=backbone_params)
    img_a = jnp.asarray(rng.uniform(-1, 1, (4, 3, 64, 64)), jnp.float32)
    img_b = jnp.clip(img_a + 0.2 * jnp.asarray(rng.normal(0, 1, (4, 3, 64, 64)), jnp.float32), -1, 1)
    lpips.update(img_a, img_b)
    lpips.update(img_a, img_a)  # identical pair contributes zero distance
    lpips_val = float(lpips.compute())
    print("LPIPS mean over 8 pairs:", round(lpips_val, 5))

    assert 0.0 < float(result["map"]) < 1.0  # shifted masks: partial credit
    assert float(result["map_50"]) > float(result["map"])
    assert lpips_val > 0.0
    print("OK")


if __name__ == "__main__":
    main()
