"""BERTScore with your own embedding model (analogue of reference
``examples/bert_score-own_model.py``).

The metric's model slot takes ANY callable stack — here a deliberately tiny
word-embedding model — via three hooks:

- ``user_tokenizer``: ``sentences -> {"input_ids", "attention_mask"}``
- ``model`` + ``user_forward_fn(model, batch) -> (B, S, D) embeddings``

so evaluation runs fully offline (hub ids also work when checkpoints are
available to transformers).

Run:
    python examples/bert_score-own_model.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp
import numpy as np

from tpumetrics.functional.text import bert_score
from tpumetrics.text import BERTScore

_PREDS = ["hello there general kenobi", "the quick brown fox jumps"]
_TARGET = ["hello there general bonjour", "the fast brown fox leaps"]


class WordTokenizer:
    """Whitespace tokenizer with a growing vocabulary (CLS=1, UNK by hash)."""

    def __init__(self, vocab_size=512):
        self.vocab_size = vocab_size

    def __call__(self, sentences):
        ids = [[1] + [2 + (hash(w) % (self.vocab_size - 2)) for w in s.split()] for s in sentences]
        return {"input_ids": ids, "attention_mask": [[1] * len(r) for r in ids]}


class HashEmbedder:
    """Deterministic embedding table keyed by token id."""

    def __init__(self, dim=64, vocab_size=512, seed=0):
        rng = np.random.default_rng(seed)
        self.table = jnp.asarray(rng.standard_normal((vocab_size, dim)), jnp.float32)

    def __call__(self, model, batch):  # user_forward_fn signature
        return self.table[jnp.asarray(batch["input_ids"])]


def main():
    tok = WordTokenizer()
    emb = HashEmbedder()

    # functional: one call, whole corpus
    scores = bert_score(_PREDS, _TARGET, model=emb, user_tokenizer=tok, user_forward_fn=emb)
    for p, t, f1 in zip(_PREDS, _TARGET, np.asarray(scores["f1"])):
        print(f"f1={f1:.4f}  {p!r} vs {t!r}")

    # module: stream corpus shards through update, score once at compute
    metric = BERTScore(model=emb, user_tokenizer=tok, user_forward_fn=emb, idf=True)
    metric.update(_PREDS[:1], _TARGET[:1])
    metric.update(_PREDS[1:], _TARGET[1:])
    out = metric.compute()
    print("streamed idf f1:", np.round(np.asarray(out["f1"]), 4).tolist())

    identical = bert_score(_PREDS, _PREDS, model=emb, user_tokenizer=tok, user_forward_fn=emb)
    assert np.allclose(np.asarray(identical["f1"]), 1.0, atol=1e-5)
    print("bert_score-own_model OK")


if __name__ == "__main__":
    main()
