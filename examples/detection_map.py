"""Object-detection evaluation with MeanAveragePrecision (analogue of
reference ``examples/detection_map.py``).

Streams per-image detections/ground truths through ``update`` — boxes stay
on device as ragged per-image arrays — then runs the COCO protocol at
``compute``. Also shows per-class results, the pairwise IoU functional,
and the packed (device-resident) dense update layout, which lands on the
same bits while staying trace-safe for the bucketed runtime.

Run:
    python examples/detection_map.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp

from tpumetrics.detection import MeanAveragePrecision
from tpumetrics.functional.detection import intersection_over_union


def main():
    preds = [
        {
            "boxes": jnp.asarray([[258.0, 41.0, 606.0, 285.0]]),
            "scores": jnp.asarray([0.536]),
            "labels": jnp.asarray([0]),
        },
        {
            "boxes": jnp.asarray([[12.0, 8.0, 64.0, 56.0], [70.0, 70.0, 120.0, 110.0]]),
            "scores": jnp.asarray([0.91, 0.45]),
            "labels": jnp.asarray([1, 0]),
        },
    ]
    target = [
        {
            "boxes": jnp.asarray([[214.0, 41.0, 562.0, 285.0]]),
            "labels": jnp.asarray([0]),
        },
        {
            "boxes": jnp.asarray([[10.0, 10.0, 60.0, 60.0], [72.0, 72.0, 118.0, 108.0]]),
            "labels": jnp.asarray([1, 0]),
        },
    ]

    metric = MeanAveragePrecision(iou_type="bbox", class_metrics=True)
    metric.update(preds, target)
    result = metric.compute()

    print(f"mAP        = {float(result['map']):.4f}")
    print(f"mAP@50     = {float(result['map_50']):.4f}")
    print(f"mAP@75     = {float(result['map_75']):.4f}")
    for cid, ap in zip(result["classes"].tolist(), result["map_per_class"].tolist()):
        print(f"  class {cid}: AP = {ap:.4f}")

    iou = intersection_over_union(preds[1]["boxes"], target[1]["boxes"], aggregate=False)
    print("pairwise IoU (image 1):")
    print(jnp.round(iou, 3))

    # the packed dense layout: one dict of (B, slots, ...) arrays per side,
    # a fixed-shape (jit-able, mesh-able) append — identical results
    from tpumetrics.detection import pack_detection_batch

    preds_dense, target_dense = pack_detection_batch(preds, target)
    packed = MeanAveragePrecision(iou_type="bbox")
    packed.update(preds_dense, target_dense)
    packed_map = float(packed.compute()["map"])
    assert packed_map == float(result["map"]), (packed_map, float(result["map"]))
    print(f"packed layout map: {packed_map:.4f} (bit-equal to the list layout)")

    assert float(result["map_50"]) > 0.5
    print("detection_map OK")


if __name__ == "__main__":
    main()
