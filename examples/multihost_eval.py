"""Multi-host (DCN) evaluation: one JAX process per host, each scoring its
shard of an eval set, metric states merged over DCN at compute.

This is the TPU-pod analogue of the reference's DDP evaluation (one torch
process per GPU, `gather_all_tensors` over NCCL at `compute`, reference
utilities/distributed.py:97-147). On a pod:

- **inside one slice (ICI)** you don't need any of this — shard the batch
  over a mesh and let `functional_compute(..., axis_name=...)` sync in-trace
  (see `train_loop_flax.py`);
- **across hosts/slices (DCN)** each process accumulates locally and the
  `MultiHostBackend` merges states eagerly at `compute()` with one padded
  all-gather per state (uneven shard sizes are fine — shapes are negotiated
  first, data is padded, gathered, and trimmed).

Run as a multi-process job (one process per host):

    # host 0
    JAX_COORDINATOR=host0:1234 JAX_PROCESS_ID=0 JAX_NUM_PROCESSES=2 python examples/multihost_eval.py
    # host 1
    JAX_COORDINATOR=host0:1234 JAX_PROCESS_ID=1 JAX_NUM_PROCESSES=2 python examples/multihost_eval.py

Run single-process (CI / laptop) and it degrades to plain local eval:

    python examples/multihost_eval.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if os.environ.get("JAX_NUM_PROCESSES"):
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
    )

import jax.numpy as jnp
import numpy as np

from tpumetrics import MetricCollection
from tpumetrics.classification import MulticlassAccuracy, MulticlassAUROC, MulticlassF1Score

NUM_CLASSES = 10


def local_shard(rank: int, world: int, n_total: int = 4096):
    """Each process reads its own shard of the eval set (here: synthesized)."""
    rng = np.random.default_rng(0)  # same stream everywhere, rank-strided rows
    logits = rng.standard_normal((n_total, NUM_CLASSES)).astype(np.float32)
    labels = rng.integers(0, NUM_CLASSES, n_total)
    return logits[rank::world], labels[rank::world]


def main() -> None:
    rank, world = jax.process_index(), jax.process_count()
    metrics = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": MulticlassF1Score(num_classes=NUM_CLASSES, average="macro"),
            "auroc": MulticlassAUROC(num_classes=NUM_CLASSES, thresholds=128),
        }
    )

    logits, labels = local_shard(rank, world)
    for lo in range(0, logits.shape[0], 256):
        metrics.update(jnp.asarray(logits[lo : lo + 256]), jnp.asarray(labels[lo : lo + 256]))

    # compute() syncs across processes automatically when jax.distributed is
    # initialized (MultiHostBackend over DCN); single-process it is local
    values = metrics.compute()
    if rank == 0:
        for name, value in values.items():
            print(f"{name}: {float(value):.4f}")
        assert 0.0 <= float(values["acc"]) <= 1.0
        print("multihost_eval OK")


if __name__ == "__main__":
    main()
