"""ROUGE with a custom normalizer + tokenizer (analogue of reference
``examples/rouge_score-own_normalizer_and_tokenizer.py``).

The default ROUGE pipeline lowercases and strips non-alphanumerics; passing
``normalizer``/``tokenizer`` callables replaces those stages — e.g. to keep
intra-word hyphens/apostrophes or to tokenize non-whitespace languages.

Run:
    python examples/rouge_score-own_normalizer_and_tokenizer.py
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpumetrics.functional.text import rouge_score

# the prediction hyphenates, the reference spells it out: the default
# pipeline strips hyphens so both sides agree, while the custom pipeline
# keeps "state-of-the-art" whole and the unigrams stop matching
_PREDS = "a state-of-the-art summary"
_TARGET = "a state of the art summary"


def hyphen_keeping_normalizer(text: str) -> str:
    """Lowercase but keep hyphens and apostrophes inside words."""
    return re.sub(r"[^a-z0-9\-']+", " ", text.lower())


def hyphen_keeping_tokenizer(text: str):
    return [tok for tok in text.split() if tok]


def main():
    default = rouge_score(_PREDS, _TARGET, rouge_keys="rouge1")
    custom = rouge_score(
        _PREDS,
        _TARGET,
        rouge_keys="rouge1",
        normalizer=hyphen_keeping_normalizer,
        tokenizer=hyphen_keeping_tokenizer,
    )

    print(f"default tokenization  rouge1_fmeasure = {float(default['rouge1_fmeasure']):.4f}")
    print(f"hyphens kept          rouge1_fmeasure = {float(custom['rouge1_fmeasure']):.4f}")

    # the default splits "state-of-the-art" into 4 tokens; the custom one
    # keeps it whole, so the two scores must differ
    assert abs(float(default["rouge1_fmeasure"]) - float(custom["rouge1_fmeasure"])) > 1e-6
    print("rouge_score-own_normalizer_and_tokenizer OK")


if __name__ == "__main__":
    main()
