"""Plotting metric values (analogue of reference ``examples/plotting.py``).

Every metric has ``.plot()``: scalar metrics render single/multi values,
confusion matrices render as annotated grids, and curve metrics (ROC,
precision-recall) render as curves. Figures are written to
``examples/_plots/`` (non-interactive Agg backend).

Run:
    python examples/plotting.py
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import matplotlib

matplotlib.use("Agg")

import jax
import jax.numpy as jnp

# TPUMETRICS_PLOT_DIR reroutes the output (tests point it at a tmpdir so a
# tier-1 run never dirties the checked-in examples/_plots/*.png)
OUT_DIR = os.environ.get("TPUMETRICS_PLOT_DIR") or os.path.join(
    os.path.dirname(__file__), "_plots"
)


def _save(fig, name):
    os.makedirs(OUT_DIR, exist_ok=True)
    path = os.path.join(OUT_DIR, name)
    fig.savefig(path)
    print("wrote", path)


def main():
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    preds = jax.nn.softmax(jax.random.normal(k1, (128, 3)), axis=-1)
    target = jax.random.randint(k2, (128,), 0, 3)

    # scalar metric over several steps -> line plot
    from tpumetrics.classification import MulticlassAccuracy

    acc = MulticlassAccuracy(num_classes=3)
    values = []
    for lo in range(0, 128, 32):
        values.append(acc(preds[lo : lo + 32], target[lo : lo + 32]))
    fig, _ = acc.plot(values)
    _save(fig, "accuracy_over_steps.png")

    # confusion matrix -> annotated grid
    from tpumetrics.classification import MulticlassConfusionMatrix

    confmat = MulticlassConfusionMatrix(num_classes=3)
    confmat.update(preds, target)
    fig, _ = confmat.plot()
    _save(fig, "confusion_matrix.png")

    # ROC -> one curve per class
    from tpumetrics.classification import MulticlassROC

    roc = MulticlassROC(num_classes=3, thresholds=None)
    roc.update(preds, target)
    fig, _ = roc.plot()
    _save(fig, "roc.png")

    print("plotting OK")


if __name__ == "__main__":
    main()
