"""Training-loop integration: a flax/optax classifier logging a
``MetricCollection`` (analogue of reference ``tests/integrations/test_lightning.py``
and ``examples/``).

The whole step — forward, loss, gradient, optimizer update, AND metric
update — is one jitted, mesh-sharded function. Metric state is an explicit
pytree threaded through the step (the functional API), so it lives on
device, shards with the data, and syncs over the mesh axis in-trace: no
host round-trips in the hot loop, which is the TPU-first redesign of the
reference's module-state + hook pattern.

The metric state rides with an EXPLICIT leading device axis
(``in/out_specs=P("dp")``, shape ``(n_dev, ...)`` outside the mesh): each
device accumulates its own shard, and the epoch-end compute psums over the
axis. A falsely-replicated ``P()`` carry happens to work in-loop (buffers
stay per-device) but a checkpoint of it would save only device 0's partial
state — the device-axis layout is what makes ``orbax`` checkpoint/resume
exact (see tests/test_lifecycle.py, which pins this pattern end-to-end).

Run (any machine; forces an 8-device CPU mesh when no 8-chip TPU exists):
    python examples/train_loop_flax.py
"""

from __future__ import annotations

import os
import sys

os.environ.setdefault("XLA_FLAGS", "") and None
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# platform must be chosen BEFORE the first backend use (jax.devices() would
# lock it in); default to the virtual 8-device CPU mesh unless the user
# explicitly picked a platform via JAX_PLATFORMS (e.g. an 8-chip TPU slice)
if not os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", "cpu")

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from tpumetrics import MetricCollection
from tpumetrics.aggregation import MeanMetric
from tpumetrics.classification import MulticlassAccuracy, MulticlassF1Score

try:  # jax >= 0.5 exposes shard_map at top level
    _shard_map = lambda f, **kw: jax.shard_map(f, check_vma=False, **kw)  # noqa: E731
    jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _sm

    _shard_map = lambda f, **kw: _sm(f, check_rep=False, **kw)  # noqa: E731

NUM_CLASSES = 10
BATCH = 512  # global batch, sharded over the dp axis
STEPS_PER_EPOCH = 20
EPOCHS = 3


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(NUM_CLASSES)(x)


def make_data(key, n=BATCH * STEPS_PER_EPOCH):
    """Linearly separable-ish synthetic classification data."""
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (n, 32))
    w_true = jax.random.normal(kw, (32, NUM_CLASSES))
    y = jnp.argmax(x @ w_true + 0.5 * jax.random.normal(kx, (n, NUM_CLASSES)), axis=-1)
    return x, y


def main():
    n_dev = min(len(jax.devices()), 8)
    assert BATCH % n_dev == 0, f"global batch {BATCH} must divide over {n_dev} devices"
    mesh = Mesh(np.asarray(jax.devices()[:n_dev]), ("dp",))
    model = MLP()
    tx = optax.adam(1e-2)

    metrics = MetricCollection(
        {
            "acc": MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False),
            "f1": MulticlassF1Score(num_classes=NUM_CLASSES, average="macro", validate_args=False),
        }
    )
    loss_metric = MeanMetric()  # different update signature -> own state

    key = jax.random.PRNGKey(0)
    params = model.init(key, jnp.zeros((1, 32)))
    opt_state = tx.init(params)
    x_all, y_all = make_data(key)

    def train_step(params, opt_state, metric_state, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean(), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        grads = jax.lax.pmean(grads, "dp")  # data-parallel gradient sync over ICI
        loss = jax.lax.pmean(loss, "dp")
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

        # metric accumulation is part of the same compiled program; the
        # state arrives as this device's (1, ...) slice of the device axis
        cls_state, loss_state = jax.tree.map(lambda a: a[0], metric_state)
        cls_state = metrics.functional_update(cls_state, logits, y)
        loss_state = loss_metric.functional_update(loss_state, loss)
        new_state = jax.tree.map(lambda a: a[None], (cls_state, loss_state))
        return params, opt_state, new_state, loss

    step = jax.jit(
        _shard_map(
            train_step,
            mesh=mesh,
            in_specs=(P(), P(), P("dp"), P("dp"), P("dp")),
            out_specs=(P(), P(), P("dp"), P()),
        ),
        donate_argnums=(2,),
    )

    def init_metric_state():
        """Per-device zeros stacked on the leading device axis — the
        checkpointable layout (every shard saved, not just device 0's)."""
        zero = (metrics.init_state(), loss_metric.init_state())
        return jax.tree.map(lambda a: jnp.broadcast_to(a[None], (n_dev,) + a.shape), zero)

    # epoch-end compute syncs the sharded metric state over the mesh axis
    @jax.jit
    def epoch_compute(metric_state):
        def _compute(state):
            cls_state, loss_state = jax.tree.map(lambda a: a[0], state)
            vals = metrics.functional_compute(cls_state, axis_name="dp")
            vals["loss"] = loss_metric.functional_compute(loss_state, axis_name="dp")
            return vals

        return _shard_map(
            _compute, mesh=mesh, in_specs=(P("dp"),), out_specs=P()
        )(metric_state)

    for epoch in range(EPOCHS):
        metric_state = init_metric_state()
        for i in range(STEPS_PER_EPOCH):
            lo = i * BATCH
            x, y = x_all[lo : lo + BATCH], y_all[lo : lo + BATCH]
            params, opt_state, metric_state, loss = step(params, opt_state, metric_state, x, y)
        vals = {k: float(v) for k, v in epoch_compute(metric_state).items()}
        print(f"epoch {epoch}: " + "  ".join(f"{k}={v:.4f}" for k, v in sorted(vals.items())))

    assert vals["acc"] > 0.5, "model should beat chance by epoch 3"
    print("train_loop_flax OK")


if __name__ == "__main__":
    main()
